"""Process-wide metric registry: counters, gauges, histograms.

Reference shape: the Prometheus client-library data model (a registry
of metric FAMILIES, each fanning out to children per label-value
tuple), because that is what every serving fleet scrapes.  Two export
surfaces:

- :meth:`MetricRegistry.prometheus_text` — the text exposition format
  (``# HELP`` / ``# TYPE`` headers, ``_bucket{le=...}``/``_sum``/
  ``_count`` triplets for histograms), deterministically ordered so
  seeded tests can assert on the exact string.
- :meth:`MetricRegistry.snapshot` — the same data as a plain JSON-able
  dict for programmatic consumers (``tools/obs_dump.py``, bench).

No background threads, no atomics beyond the GIL: producers are the
single-threaded scheduler / train loop, and the registry is swapped
wholesale by ``obs.configure`` rather than mutated concurrently.
"""
from __future__ import annotations

import math
import re

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Prometheus default latency buckets (seconds) — wide enough for both
#: sub-ms scheduler ticks and multi-second compiles.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _check_name(kind, name, regex=_NAME_RE):
    if not regex.match(name):
        raise ValueError(f"invalid {kind} name {name!r}")


def _fmt(v):
    """Deterministic sample rendering: integral values print as ints
    (``3`` not ``3.0``), the rest via repr of the float."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


class _Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counters only go up; inc({n})")
        self.value += n


class _Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def inc(self, n=1):
        self.value += n

    def dec(self, n=1):
        self.value -= n


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets):
        self.buckets = buckets            # ascending upper bounds
        self.counts = [0] * (len(buckets) + 1)  # + overflow (+Inf)
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        v = float(v)
        self.sum += v
        self.count += 1
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


_CHILD = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class Family:
    """One named metric family; children keyed by label-value tuple.

    A family declared with no label names acts as its own single child:
    ``registry.counter("x").inc()`` works without ``.labels()``.
    """

    def __init__(self, name, mtype, help="", labelnames=(),
                 buckets=None):
        _check_name("metric", name)
        self.name = name
        self.type = mtype
        self.help = help
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            _check_name("label", ln, _LABEL_RE)
        self.buckets = (tuple(buckets) if buckets is not None
                        else DEFAULT_BUCKETS)
        if mtype == "histogram" and \
                list(self.buckets) != sorted(self.buckets):
            raise ValueError(f"histogram buckets must ascend: "
                             f"{self.buckets}")
        self._children = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.type == "histogram":
            return _Histogram(self.buckets)
        return _CHILD[self.type]()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = self._make_child()
        return child

    # -- label-less convenience (proxy to the default child) ------------

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels {self.labelnames};"
                             f" use .labels(...)")
        return self._children[()]

    def inc(self, n=1):
        self._default().inc(n)

    def set(self, v):
        self._default().set(v)

    def dec(self, n=1):
        self._default().dec(n)

    def observe(self, v):
        self._default().observe(v)


class MetricRegistry:
    """Name -> :class:`Family`; declaration is idempotent (the same
    name with the same type/labels returns the existing family, a
    conflicting redeclaration raises)."""

    def __init__(self):
        self._families = {}

    def _declare(self, name, mtype, help, labels, buckets=None):
        fam = self._families.get(name)
        if fam is not None:
            if fam.type != mtype or fam.labelnames != tuple(labels):
                raise ValueError(
                    f"metric {name!r} redeclared as {mtype}"
                    f"{tuple(labels)} (was {fam.type}{fam.labelnames})")
            return fam
        fam = Family(name, mtype, help=help, labelnames=labels,
                     buckets=buckets)
        self._families[name] = fam
        return fam

    def counter(self, name, help="", labels=()):
        return self._declare(name, "counter", help, labels)

    def gauge(self, name, help="", labels=()):
        return self._declare(name, "gauge", help, labels)

    def histogram(self, name, help="", labels=(), buckets=None):
        return self._declare(name, "histogram", help, labels, buckets)

    def get(self, name):
        return self._families.get(name)

    # -- export ----------------------------------------------------------

    @staticmethod
    def _labelstr(labelnames, key, extra=None):
        # label keys sorted by name: the exposition never depends on
        # declaration order
        parts = [f'{ln}="{_escape(v)}"'
                 for ln, v in sorted(zip(labelnames, key))]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def prometheus_text(self):
        lines = []
        for name in sorted(self._families):
            fam = self._families[name]
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.type}")
            for key in sorted(fam._children):
                child = fam._children[key]
                if fam.type == "histogram":
                    cum = 0
                    for ub, c in zip(fam.buckets, child.counts):
                        cum += c
                        ls = self._labelstr(fam.labelnames, key,
                                            f'le="{_fmt(ub)}"')
                        lines.append(f"{name}_bucket{ls} {cum}")
                    ls = self._labelstr(fam.labelnames, key, 'le="+Inf"')
                    lines.append(f"{name}_bucket{ls} {child.count}")
                    ls = self._labelstr(fam.labelnames, key)
                    lines.append(f"{name}_sum{ls} {_fmt(child.sum)}")
                    lines.append(f"{name}_count{ls} {child.count}")
                else:
                    ls = self._labelstr(fam.labelnames, key)
                    lines.append(f"{name}{ls} {_fmt(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self):
        out = {}
        for name in sorted(self._families):
            fam = self._families[name]
            samples = []
            for key in sorted(fam._children):
                child = fam._children[key]
                labels = dict(zip(fam.labelnames, key))
                if fam.type == "histogram":
                    samples.append({
                        "labels": labels,
                        "buckets": {_fmt(ub): c for ub, c in
                                    zip(fam.buckets, child.counts)},
                        "overflow": child.counts[-1],
                        "sum": child.sum,
                        "count": child.count,
                    })
                else:
                    samples.append({"labels": labels,
                                    "value": child.value})
            out[name] = {"type": fam.type, "help": fam.help,
                         "samples": samples}
        return out
