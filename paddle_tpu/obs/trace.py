"""Structured trace spans with per-request trace IDs.

Spans are host-side (name, cat, ts, dur, args) records kept in a
bounded deque and exported as Chrome-trace JSON (``{"traceEvents":
[...]}``, timestamps in microseconds) — the format Perfetto and
``chrome://tracing`` open directly.  Every live span also enters a
``jax.profiler.TraceAnnotation`` so the same names appear on the
device timeline when a ``jax.profiler.start_trace`` session is
running: load both files in Perfetto and the host span brackets its
device work.

The clock is injectable.  ``LogicalClock`` is a deterministic
auto-advancing counter so seeded tests assert exact timestamps and
durations; production uses ``time.perf_counter``.
"""
from __future__ import annotations

import json
from collections import deque

import jax


class LogicalClock:
    """Deterministic clock for seeded tests: every read advances by
    ``tick``, so the n-th read is exactly ``start + n * tick`` and any
    derived duration/percentile is a closed-form number."""

    def __init__(self, start=0.0, tick=0.001):
        self.t = float(start)
        self.tick = float(tick)
        self.reads = 0

    def __call__(self):
        self.reads += 1
        self.t += self.tick
        return self.t


class Span:
    """One completed span (``dur`` in seconds), instant (``dur`` None)
    or counter sample (``ph="C"``; ``args`` holds the series values).
    ``args`` carries structured payload — ``trace_id`` rides there so
    Perfetto shows it on every slice."""

    __slots__ = ("name", "cat", "ts", "dur", "args", "ph")

    def __init__(self, name, cat, ts, dur, args, ph=None):
        self.name = name
        self.cat = cat
        self.ts = ts
        self.dur = dur
        self.args = args
        self.ph = ph

    def __repr__(self):
        kind = ("counter" if self.ph == "C"
                else "instant" if self.dur is None
                else f"dur={self.dur:.6f}")
        return f"Span({self.name}, {kind}, args={self.args})"


class _LiveSpan:
    """Context manager handed out by :meth:`Tracer.span`; completes
    into the tracer's ring on exit.  ``set(**kv)`` attaches args only
    known mid-span (e.g. the step's loss)."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0", "_ann")

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = None
        self._ann = None

    def set(self, **kv):
        self.args.update(kv)
        return self

    def __enter__(self):
        if self._tracer.annotate:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        self._t0 = self._tracer._clock()
        return self

    def __exit__(self, *exc):
        t1 = self._tracer._clock()
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        self._tracer._push(Span(self.name, self.cat, self._t0,
                                t1 - self._t0, self.args))
        return False


class Tracer:
    """Bounded span collector + Chrome-trace exporter."""

    def __init__(self, clock, capacity=65536, annotate=True):
        self._clock = clock
        self.capacity = int(capacity)
        self.annotate = bool(annotate)
        self.spans = deque(maxlen=self.capacity)
        self.dropped = 0
        self.pid = 0

    def _push(self, span):
        if len(self.spans) == self.spans.maxlen:
            self.dropped += 1
        self.spans.append(span)

    def span(self, name, cat="host", trace_id=None, **args):
        if trace_id is not None:
            args["trace_id"] = trace_id
        return _LiveSpan(self, name, cat, args)

    def instant(self, name, cat="host", trace_id=None, **args):
        if trace_id is not None:
            args["trace_id"] = trace_id
        self._push(Span(name, cat, self._clock(), None, args))

    def counter(self, name, cat="host", **values):
        """One counter-track sample (Chrome ``"ph": "C"``): each kwarg
        becomes a named series on the track, so Perfetto renders e.g.
        MFU / HBM-GB/s as stacked graphs above the span rows."""
        self._push(Span(name, cat, self._clock(), None, values, ph="C"))

    # -- export ----------------------------------------------------------

    def to_chrome_events(self):
        """Spans as Chrome-trace event dicts (ts/dur in microseconds).
        Training spans land on tid 0, serving on tid 1, so the two
        subsystems render as separate rows in Perfetto."""
        events = [{"ph": "M", "name": "process_name", "pid": self.pid,
                   "tid": 0,
                   "args": {"name": "paddle_tpu host telemetry"}}]
        for tid, label in ((0, "train"), (1, "serving")):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": self.pid, "tid": tid,
                           "args": {"name": label}})
        for s in self.spans:
            tid = 1 if s.cat.startswith("serve") else 0
            ev = {"name": s.name, "cat": s.cat, "pid": self.pid,
                  "tid": tid, "ts": round(s.ts * 1e6, 3),
                  "args": dict(s.args)}
            if s.ph == "C":
                ev["ph"] = "C"
            elif s.dur is None:
                ev["ph"] = "i"
                ev["s"] = "t"  # thread-scoped instant
            else:
                ev["ph"] = "X"
                ev["dur"] = round(s.dur * 1e6, 3)
            events.append(ev)
        return events

    def export_chrome(self, path):
        """Write the Chrome-trace JSON; returns ``path``.  Bracketed by
        the ``obs.export`` fault point (serviceability tests inject a
        raise/crash here)."""
        from ..testing import faults

        faults.fire("obs.export", "before", path=path)
        doc = {"traceEvents": self.to_chrome_events(),
               "displayTimeUnit": "ms"}
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
        faults.fire("obs.export", "after", path=path)
        return path
