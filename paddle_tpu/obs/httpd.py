"""Zero-dependency HTTP exposition for the health plane.

A stdlib :class:`http.server.ThreadingHTTPServer` on a daemon thread,
serving three read-only endpoints off the live obs bundle:

- ``/metrics``  — Prometheus text exposition from the metric registry
- ``/healthz``  — liveness + last-step staleness (200 ok / 503 stale)
- ``/statusz``  — JSON: build info, SLO table, roofline rows,
  pool/occupancy providers, heartbeats, event-log position

Gated by ``PT_OBS_HTTP=<port>`` (auto-started when the telemetry
bundle is built with that set); tests start one explicitly on an
ephemeral port via :func:`start` / ``port=0``.  The handler resolves
``obs.handle()`` lazily per request, so a scrape while telemetry is
off gets a clean 503 instead of a crash, and ``obs.configure`` swaps
under a running server without a restart.

Every request is bracketed by the ``obs.http`` fault point; an armed
``raise`` surfaces as a 500 response and the NEXT request succeeds —
the serving process must never die because monitoring hiccuped.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    # quiet: a scrape per second must not spam stderr
    def log_message(self, fmt, *args):
        pass

    def _send(self, code, body, content_type="application/json"):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code, payload):
        self._send(code, json.dumps(payload, default=str, indent=1))

    def do_GET(self):
        from ..testing.faults import fire

        try:
            fire("obs.http", "before", path=self.path)
            self._route()
            fire("obs.http", "after", path=self.path)
        except Exception as e:
            # one bad request (injected or organic) must not take the
            # server down; report and keep listening
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:
                pass

    def _route(self):
        from .. import obs
        from . import health

        path = self.path.split("?", 1)[0]
        h = obs.handle()
        if h is None:
            self._send_json(503, {"error": "telemetry off (PT_OBS)"})
            return
        if path == "/metrics":
            self._send(200, h.registry.prometheus_text(),
                       content_type=PROMETHEUS_CONTENT_TYPE)
        elif path == "/healthz":
            ok, payload = health.healthz_payload(h)
            self._send_json(200 if ok else 503, payload)
        elif path == "/statusz":
            self._send_json(200, health.statusz_payload(h))
        else:
            self._send_json(404, {
                "error": f"no route {path!r}",
                "routes": ["/metrics", "/healthz", "/statusz"]})


class ObsHTTPServer:
    """The background exposition server; one per obs bundle."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"pt-obs-httpd:{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


def start(port=0, host="127.0.0.1"):
    """Start (or return the already-running) exposition server for the
    live bundle.  ``port=0`` binds an ephemeral port (tests).  Returns
    the :class:`ObsHTTPServer`, or ``None`` when telemetry is off."""
    from .. import obs

    h = obs.handle()
    if h is None:
        return None
    if h.httpd is None:
        h.httpd = ObsHTTPServer(port=port, host=host)
    return h.httpd


def stop():
    """Stop the live bundle's server, if any."""
    from .. import obs

    h = obs.handle()
    if h is not None and h.httpd is not None:
        h.httpd.stop()
        h.httpd = None
