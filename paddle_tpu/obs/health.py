"""Serving health plane: declarative SLOs, error-budget burn-rate
alerts, and the payloads behind ``/healthz`` / ``/statusz``.

The model is the SRE multi-window multi-burn-rate recipe: an
objective declares a target fraction of good events (e.g. "99% of
requests see TTFT <= 250 ms"), the error budget is ``1 - target``, and
the burn rate over a window is the observed bad fraction divided by
the budget (burn 1.0 = spending exactly the budget; 14.4 over a 5 m
and a 1 h window together = the classic page-now pair).  An alert rule
fires only when BOTH its short and long window exceed the threshold —
the short window gives fast detection, the long one keeps a brief
blip from paging.

Everything reads the obs clock and the metric registry, so on a
:class:`~paddle_tpu.obs.trace.LogicalClock` the whole plane — burn
values, fire/resolve steps — is exact and unit-testable.  Objectives
read CUMULATIVE counters and take window deltas between snapshots, so
evaluation frequency only affects resolution, never correctness.

Exported series::

    slo_burn_rate{slo,window}      # per evaluated window
    slo_budget_remaining{slo}      # over the longest rule window
    slo_alert_state{slo}           # 0=ok 1=warn 2=page

State transitions emit ``alert.fire`` / ``alert.resolve`` flight
events (which tee into the structured event log).
"""
from __future__ import annotations

import os
import sys
from collections import deque, namedtuple

#: (short_s, long_s, threshold, severity) — fires when the burn rate
#: over BOTH windows is >= threshold.
BurnRule = namedtuple("BurnRule", "short_s long_s threshold severity")

#: Google SRE defaults: fast 5m/1h pair pages at 14.4x budget burn,
#: slow 6h/3d pair warns at 1.0x (budget exhausted on trend).
DEFAULT_BURN_RULES = (
    BurnRule(short_s=300.0, long_s=3600.0, threshold=14.4,
             severity="page"),
    BurnRule(short_s=21600.0, long_s=259200.0, threshold=1.0,
             severity="warn"),
)

SEVERITY_RANK = {"ok": 0, "warn": 1, "page": 2}


def _check_target(name, target):
    if not 0.0 < target < 1.0:
        raise ValueError(f"SLO {name!r}: target must be in (0, 1), "
                         f"got {target}")


class LatencyObjective:
    """"``target`` fraction of observations land at or below
    ``threshold_s``" over a registry histogram family.

    ``threshold_s`` must be one of the family's bucket upper bounds —
    the good-count is then exact (cumulative bucket count), not an
    interpolation.  A mismatched threshold raises at first read.
    """

    def __init__(self, name, family, threshold_s, target):
        _check_target(name, target)
        self.name = name
        self.family = family
        self.threshold_s = float(threshold_s)
        self.target = float(target)

    def read(self, registry):
        """Cumulative ``(bad, total)`` summed over all children."""
        fam = registry.get(self.family)
        if fam is None:
            return 0, 0
        try:
            idx = fam.buckets.index(self.threshold_s)
        except ValueError:
            raise ValueError(
                f"SLO {self.name!r}: threshold {self.threshold_s} is "
                f"not a bucket bound of {self.family} "
                f"(buckets: {fam.buckets})")
        good = total = 0
        for child in fam._children.values():
            good += sum(child.counts[:idx + 1])
            total += child.count
        return total - good, total

    def describe(self):
        return {"kind": "latency", "family": self.family,
                "threshold_s": self.threshold_s}


class RatioObjective:
    """"At most ``1 - target`` of events are bad" over two counter
    selectors.

    ``bad`` / ``total`` are ``(family, labels)`` pairs; ``labels`` is a
    subset filter over the family's children (``None`` = sum all).
    """

    def __init__(self, name, bad, total, target):
        _check_target(name, target)
        self.name = name
        self.bad = bad
        self.total = total
        self.target = float(target)

    @staticmethod
    def _sum(registry, selector):
        family, labels = selector
        fam = registry.get(family)
        if fam is None:
            return 0.0
        acc = 0.0
        for key, child in fam._children.items():
            if labels:
                child_labels = dict(zip(fam.labelnames, key))
                if any(child_labels.get(k) != str(v)
                       for k, v in labels.items()):
                    continue
            acc += child.value
        return acc

    def read(self, registry):
        return (self._sum(registry, self.bad),
                self._sum(registry, self.total))

    def describe(self):
        return {"kind": "ratio", "bad": list(self.bad[0:1]) + [
            self.bad[1] or {}], "total": self.total[0]}


def default_serving_slos():
    """The stock serving objectives: TTFT p99 <= 250 ms and request
    error rate <= 0.1%."""
    return [
        LatencyObjective("serve_ttft", "serve_ttft_seconds",
                         threshold_s=0.25, target=0.99),
        RatioObjective(
            "serve_errors",
            bad=("serve_requests_total", {"state": "failed"}),
            total=("serve_requests_submitted_total", None),
            target=0.999),
    ]


def default_train_slos():
    """The stock training objective: at most 1% of optimizer steps
    flagged anomalous by the guardian (NaN/Inf loss, grad blowup,
    loss spike)."""
    return [
        RatioObjective(
            "train_anomalies",
            bad=("guardian_anomalies_total", None),
            total=("train_steps_total", None),
            target=0.99),
    ]


class SLOEngine:
    """Evaluates objectives against the registry, maintains the
    per-SLO burn-rate windows, and runs the OK→WARN→PAGE alert state
    machine.

    Built only when telemetry is on (callers follow the producer
    idiom: check ``obs.handle()`` first).  ``evaluate`` is driven from
    the owner's step loop — ``ServingEngine.step`` and ``Model.fit``.
    """

    def __init__(self, objectives, rules=DEFAULT_BURN_RULES,
                 handle=None, source="serving", now=None):
        if handle is None:
            from .. import obs
            handle = obs.handle()
        if handle is None:
            raise RuntimeError("SLOEngine requires telemetry on "
                               "(obs.handle() is None)")
        self._h = handle
        self.source = source
        self.objectives = list(objectives)
        self.rules = tuple(BurnRule(*r) for r in rules)
        if not self.rules:
            raise ValueError("SLOEngine needs at least one BurnRule")
        for r in self.rules:
            if r.severity not in ("warn", "page"):
                raise ValueError(f"unknown severity {r.severity!r}")
            if r.short_s > r.long_s:
                raise ValueError(f"rule windows must be short<=long: {r}")
        self.windows = tuple(sorted({w for r in self.rules
                                     for w in (r.short_s, r.long_s)}))
        self._max_window = max(self.windows)
        r = handle.registry
        self._g_burn = r.gauge(
            "slo_burn_rate",
            "Error-budget burn rate per SLO and window",
            labels=("slo", "window"))
        self._g_budget = r.gauge(
            "slo_budget_remaining",
            "Fraction of error budget left over the longest window",
            labels=("slo",))
        self._g_state = r.gauge(
            "slo_alert_state", "Alert state: 0=ok 1=warn 2=page",
            labels=("slo",))
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._samples = {}   # name -> deque[(t, bad, total)]
        self._state = {}
        self._last = {}      # name -> latest table row
        t0 = handle.clock() if now is None else now
        for obj in self.objectives:
            bad, total = obj.read(r)
            self._samples[obj.name] = deque([(t0, bad, total)])
            self._state[obj.name] = "ok"
            self._g_state.labels(slo=obj.name).set(0)
        # newest engine wins per source (same convention as statusz
        # providers): rebuilding a ServingEngine or re-entering fit
        # must not accumulate stale SLO rows
        handle.slo_engines[:] = [e for e in handle.slo_engines
                                 if e.source != source] + [self]

    # -- burn math ------------------------------------------------------

    @staticmethod
    def _baseline(dq, cutoff):
        """Latest sample at or before ``cutoff``; the oldest retained
        sample when the window predates history."""
        base = dq[0]
        for s in dq:
            if s[0] <= cutoff:
                base = s
            else:
                break
        return base

    def _burn(self, dq, now, window, budget):
        t_b, bad_b, total_b = self._baseline(dq, now - window)
        t_n, bad_n, total_n = dq[-1]
        d_total = total_n - total_b
        if d_total <= 0:
            return 0.0
        return ((bad_n - bad_b) / d_total) / budget

    # -- the step hook --------------------------------------------------

    def evaluate(self, step=None, now=None):
        """Take one snapshot of every objective, update burn gauges,
        and advance the alert state machine.  ``step`` is the owner's
        logical step, stamped into alert events so deterministic tests
        can assert the exact firing step; owners driving a hot loop
        pass ``now`` (a timestamp they already read) so evaluation
        adds no clock reads."""
        h = self._h
        if now is None:
            now = h.clock()
        for obj in self.objectives:
            budget = 1.0 - obj.target
            bad, total = obj.read(h.registry)
            dq = self._samples[obj.name]
            dq.append((now, bad, total))
            # keep one sample older than the longest window as the
            # baseline; drop the rest of the stale prefix
            while len(dq) >= 2 and dq[1][0] <= now - self._max_window:
                dq.popleft()
            burns = {w: self._burn(dq, now, w, budget)
                     for w in self.windows}
            for w, b in burns.items():
                self._g_burn.labels(slo=obj.name,
                                    window=f"{w:g}s").set(b)
            remaining = 1.0 - burns[self._max_window]
            self._g_budget.labels(slo=obj.name).set(remaining)

            new_state = "ok"
            for rule in self.rules:
                if (burns[rule.short_s] >= rule.threshold
                        and burns[rule.long_s] >= rule.threshold
                        and SEVERITY_RANK[rule.severity]
                        > SEVERITY_RANK[new_state]):
                    new_state = rule.severity
            old_state = self._state[obj.name]
            if new_state != old_state:
                self._state[obj.name] = new_state
                self._g_state.labels(slo=obj.name).set(
                    SEVERITY_RANK[new_state])
                rising = (SEVERITY_RANK[new_state]
                          > SEVERITY_RANK[old_state])
                h.recorder.record(
                    "alert.fire" if rising else "alert.resolve",
                    slo=obj.name, source=self.source, step=step,
                    severity=new_state,
                    burn=round(max(burns.values()), 4),
                    **{"from": old_state, "to": new_state})
            self._last[obj.name] = {
                "slo": obj.name,
                "source": self.source,
                "target": obj.target,
                "state": self._state[obj.name],
                "burn": {f"{w:g}s": round(b, 4)
                         for w, b in burns.items()},
                "budget_remaining": round(remaining, 4),
                "bad": bad,
                "total": total,
                "objective": obj.describe(),
            }
        return self.table()

    def state(self, name):
        return self._state[name]

    def table(self):
        """Latest per-SLO rows (the ``/statusz`` SLO table)."""
        return [self._last.get(o.name,
                               {"slo": o.name, "source": self.source,
                                "target": o.target, "state": "ok",
                                "burn": {}, "budget_remaining": 1.0,
                                "bad": 0, "total": 0,
                                "objective": o.describe()})
                for o in self.objectives]


# -- endpoint payloads (shared by httpd and tools) -----------------------

def build_info():
    import jax

    from .. import __version__ as pt_version
    return {"project": "paddle_tpu", "version": pt_version,
            "python": sys.version.split()[0], "jax": jax.__version__}


def healthz_payload(handle, stale_after_s=None):
    """Liveness + last-step staleness.  Returns ``(ok, payload)``;
    a component is stale when its heartbeat is older than
    ``stale_after_s`` (env ``PT_OBS_STALE_S``, default 600)."""
    if stale_after_s is None:
        stale_after_s = float(os.environ.get("PT_OBS_STALE_S", "600"))
    now = handle.clock()
    components = {}
    ok = True
    for name, ts in sorted(handle.heartbeats.items()):
        age = now - ts
        stale = age > stale_after_s
        ok = ok and not stale
        components[name] = {"last_beat_ts": round(ts, 6),
                            "age_s": round(age, 6), "stale": stale}
    return ok, {"status": "ok" if ok else "stale",
                "now": round(now, 6),
                "stale_after_s": stale_after_s,
                "components": components}


def statusz_payload(handle):
    """The ``/statusz`` JSON: build info, heartbeats, the SLO table
    from every live :class:`SLOEngine`, and per-component provider
    payloads (pool/occupancy/roofline from the serving engine, step
    phases from training)."""
    slos = []
    for eng in handle.slo_engines:
        slos.extend(eng.table())
    providers = {}
    for name in sorted(handle.statusz):
        try:
            providers[name] = handle.statusz[name]()
        except Exception as e:  # a dead provider must not kill /statusz
            providers[name] = {"error": repr(e)}
    return {
        "build": build_info(),
        "now": round(handle.clock(), 6),
        "heartbeats": {k: round(v, 6)
                       for k, v in sorted(handle.heartbeats.items())},
        "slos": slos,
        "providers": providers,
        "event_log": {"seq": handle.events.seq,
                      "tail": len(handle.events),
                      "path": handle.events.path},
    }
