"""Flight recorder: a bounded ring-buffer journal of structured events.

The black-box recorder pattern: producers append cheap dict events
(guardian skips/rollbacks, preemptions, evictions, COW copies,
retraces, fault firings) into a ``deque(maxlen=capacity)``; nothing is
written anywhere until something goes wrong.  On ``GuardianAbort``, a
request failure, or an explicit ``obs.dump()`` the ring is serialized
as JSON lines — one header line naming the dump reason, then the last
N events oldest-first.

``seq`` increments monotonically for the life of the recorder and
SURVIVES ring overflow, so a dump proves both the bound (at most
``capacity`` events) and the ordering (strictly increasing ``seq``,
ending at the global event count).
"""
from __future__ import annotations

import json
from collections import deque


class FlightRecorder:
    def __init__(self, clock, capacity=512, sink=None):
        self._clock = clock
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._events = deque(maxlen=self.capacity)
        self.seq = 0              # total events ever recorded
        self.dumps = 0
        self.last_dump = None     # text of the most recent dump
        # sink: tee every ring event into the structured event log —
        # the ring stays the bounded crash black box, the sink keeps
        # the durable journal (obs wires this to EventLog.from_flight)
        self._sink = sink

    def record(self, kind, **fields):
        self.seq += 1
        ev = {"seq": self.seq, "ts": round(self._clock(), 6),
              "kind": kind}
        ev.update(fields)
        self._events.append(ev)
        if self._sink is not None:
            self._sink(ev)
        return ev

    def events(self):
        return list(self._events)

    def __len__(self):
        return len(self._events)

    def dump(self, path=None, reason="manual", extra=None):
        """Serialize the ring as JSON lines; returns the text.  Writes
        to ``path`` when given.  Bracketed by the ``obs.dump`` fault
        point so crash-during-dump is itself testable."""
        from ..testing import faults

        faults.fire("obs.dump", "before", path=path)
        header = {"flight_recorder": {
            "reason": reason,
            "capacity": self.capacity,
            "total_events": self.seq,
            "dumped": len(self._events),
        }}
        if extra:
            header["flight_recorder"]["extra"] = extra
        lines = [json.dumps(header, default=str)]
        lines.extend(json.dumps(ev, default=str) for ev in self._events)
        text = "\n".join(lines) + "\n"
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        self.last_dump = text
        self.dumps += 1
        faults.fire("obs.dump", "after", path=path)
        return text
