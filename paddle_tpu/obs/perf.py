"""Roofline/MFU attribution: analytical cost × measured wall time.

The cost model (``analysis.cost``) prices every registered
:class:`ProgramContract` once; this module joins those static numbers
with runtime signals — step wall time from the producers / Tracer
spans, HBM watermarks from ``device.memory`` — and publishes the
result through the obs plane:

* gauges ``program_mfu{program}``, ``program_hbm_gbps{program}``,
  ``program_flops{program}``, ``roofline_bound{program,bound}``
  (1 on the active classification, 0 on the other),
  ``hbm_peak_bytes`` / ``hbm_bytes_in_use`` / ``hbm_bytes_limit``,
  and ``step_phase_seconds{program,phase}`` from :class:`StepTimer`;
* Perfetto counter tracks (``perf.mfu``, ``perf.hbm``) in the
  Chrome-trace export via ``Tracer.counter``.

Everything here is behind the same ``PT_OBS`` gate as the rest of the
plane: with obs off every entry point is one ``None`` check, and with
obs on the join must stay inside the ≤3% ``obs_overhead`` bench
contract — hence the cost trace is cached on the contract (first call
only, normally absorbed by the warmup/compile step), HBM sampling is
throttled to every :data:`HBM_SAMPLE_EVERY` publishes (the no-stats
fallback walks ``jax.live_arrays()``), and attribution failures are
remembered so a broken program never re-prices per step.
"""
from __future__ import annotations

import jax

#: Per-chip peak dense FLOP/s (bf16) by device_kind substring.  One
#: table for the whole repo — bench.py delegates here.
PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5", 197e12),   # v5e / v5 lite family
    ("v4", 275e12),
    ("cpu", 1e12),    # nominal, keeps CPU-run MFU figures finite
)

#: Per-chip peak HBM bandwidth (bytes/s) by device_kind substring.
PEAK_HBM_BYTES_S = (
    ("v6", 1638e9),
    ("v5p", 2765e9),
    ("v5", 819e9),
    ("v4", 1228e9),
    ("cpu", 50e9),    # nominal DDR-class figure
)

#: Publish HBM watermarks every N-th on_program/end_step call per
#: program: the live-array fallback on statless backends is O(arrays).
HBM_SAMPLE_EVERY = 16

_hbm_calls = {}          # program -> publish-call count
_failed_cost = set()     # programs whose cost trace raised: don't retry


def _device_kind():
    try:
        d = jax.devices()[0]
        return (getattr(d, "device_kind", "") or d.platform).lower()
    except Exception:
        return "cpu"


def _lookup(table, kind):
    for sub, v in table:
        if sub in kind:
            return v
    return table[-1][1]


def peak_flops_per_chip(device_kind=None):
    """Peak dense FLOP/s for one chip (bf16), from the device kind."""
    return _lookup(PEAK_FLOPS, (device_kind or _device_kind()).lower())


def peak_hbm_bytes_s(device_kind=None):
    """Peak HBM bandwidth (bytes/s) for one chip."""
    return _lookup(PEAK_HBM_BYTES_S,
                   (device_kind or _device_kind()).lower())


def ridge_intensity(device_kind=None):
    """FLOPs/byte at the roofline ridge: programs above it are
    compute-bound, below it bandwidth-bound."""
    kind = (device_kind or _device_kind()).lower()
    return peak_flops_per_chip(kind) / peak_hbm_bytes_s(kind)


def program_cost(name):
    """CostReport for a registered program, or None (unknown program,
    lazy shapes not captured yet, or a previously failed trace)."""
    if name in _failed_cost:
        return None
    from ..analysis import registered

    contract = registered().get(name)
    if contract is None:
        return None
    try:
        return contract.cost()
    except Exception:
        # A program whose cost trace raises must never break (or keep
        # re-pricing inside) the train/serve step.
        _failed_cost.add(name)
        return None


def roofline(cost, wall_s, device_kind=None):
    """Join one CostReport with a measured wall time.

    Returns ``{mfu, hbm_gbps, intensity, bound, flops, hbm_bytes}``;
    ``bound`` classifies against the machine ridge point."""
    if cost is None or wall_s is None or wall_s <= 0:
        return None
    kind = (device_kind or _device_kind()).lower()
    achieved_flops_s = cost.flops / wall_s
    return {
        "mfu": achieved_flops_s / peak_flops_per_chip(kind),
        "hbm_gbps": cost.hbm_bytes / wall_s / 1e9,
        "intensity": cost.arithmetic_intensity,
        "bound": ("compute"
                  if cost.arithmetic_intensity >= ridge_intensity(kind)
                  else "bandwidth"),
        "flops": cost.flops,
        "hbm_bytes": cost.hbm_bytes,
        "wall_s": wall_s,
    }


def _publish(h, name, rl):
    reg = h.registry
    reg.gauge("program_mfu", "Model FLOP utilization per program",
              labels=("program",)).labels(program=name).set(rl["mfu"])
    reg.gauge("program_hbm_gbps", "Achieved HBM GB/s per program",
              labels=("program",)).labels(program=name) \
       .set(rl["hbm_gbps"])
    reg.gauge("program_flops", "Analytical FLOPs per program call",
              labels=("program",)).labels(program=name).set(rl["flops"])
    bound = reg.gauge("roofline_bound",
                      "1 on the active roofline classification",
                      labels=("program", "bound"))
    for b in ("compute", "bandwidth"):
        bound.labels(program=name, bound=b).set(
            1.0 if rl["bound"] == b else 0.0)
    h.tracer.counter("perf.mfu", cat="perf",
                     **{name: round(rl["mfu"], 6)})
    h.tracer.counter("perf.hbm", cat="perf",
                     **{name: round(rl["hbm_gbps"], 3)})


def sample_hbm(h=None):
    """Publish HBM watermark gauges (unthrottled — callers throttle)."""
    from paddle_tpu import obs

    h = h if h is not None else obs.handle()
    if h is None:
        return None
    try:
        from ..device import memory

        wm = memory.watermarks()
    except Exception:
        return None
    reg = h.registry
    reg.gauge("hbm_bytes_in_use", "Current HBM bytes in use") \
       .set(wm["bytes_in_use"])
    reg.gauge("hbm_peak_bytes", "Peak HBM bytes in use") \
       .set(wm["peak_bytes_in_use"])
    reg.gauge("hbm_bytes_limit", "HBM capacity") \
       .set(wm["bytes_limit"])
    h.tracer.counter("perf.hbm_bytes", cat="perf",
                     in_use=wm["bytes_in_use"],
                     peak=wm["peak_bytes_in_use"])
    return wm


def on_program(name, wall_s):
    """Producer entry point: attribute one timed call of a registered
    program.  No-op when obs is off, when the program has no cost yet
    (lazy shapes), or when pricing previously failed."""
    from paddle_tpu import obs

    h = obs.handle()
    if h is None:
        return None
    rl = roofline(program_cost(name), wall_s)
    if rl is None:
        return None
    _publish(h, name, rl)
    n = _hbm_calls.get(name, 0)
    _hbm_calls[name] = n + 1
    if n % HBM_SAMPLE_EVERY == 0:
        sample_hbm(h)
    return rl


def attribute_from_tracer(mapping=None, min_spans=1):
    """Pull-model attribution for programs timed by existing spans
    (the serving scheduler): scan the tracer ring, join mean span wall
    time per name with the program's cost, publish, and return
    ``{program: roofline_dict}``.

    ``mapping`` renames span → program (e.g. ``{"req.prefill":
    "serve.prefill"}``); span names that already match a registered
    program need no entry.  Zero hot-path cost: call at stats/export
    time, not per step."""
    from paddle_tpu import obs

    h = obs.handle()
    if h is None:
        return {}
    from ..analysis import registered

    names = set(registered())
    mapping = dict(mapping or {})
    walls = {}   # program -> [durations]
    for s in h.tracer.spans:
        if s.dur is None:
            continue
        prog = mapping.get(s.name, s.name if s.name in names else None)
        if prog is not None:
            walls.setdefault(prog, []).append(s.dur)
    out = {}
    for prog, durs in sorted(walls.items()):
        if len(durs) < min_spans:
            continue
        rl = roofline(program_cost(prog), sum(durs) / len(durs))
        if rl is None:
            continue
        rl["spans"] = len(durs)
        _publish(h, prog, rl)
        out[prog] = rl
    return out


class StepTimer:
    """Per-step phase breakdown (data-wait / compute / checkpoint /
    obs) for the train loop.

    Null-safe: with obs off every method is one attribute check.  Use::

        timer = StepTimer("train.step")
        with timer.phase("data_wait"):
            batch = next(loader)
        with timer.phase("compute"):
            loss = step(batch)
        timer.end_step()   # publishes phase gauges + roofline

    ``end_step`` publishes ``step_phase_seconds{program,phase}`` per
    phase and, when the program has a cost, the roofline gauges from
    the compute-phase wall time (compute is what the analytical FLOPs
    model; data-wait/checkpoint/obs are host overhead)."""

    PHASES = ("data_wait", "compute", "checkpoint", "obs")

    def __init__(self, program="train.step"):
        self.program = program
        self.steps = 0
        self._acc = {}

    class _Phase:
        __slots__ = ("timer", "name", "_t0", "_clock")

        def __init__(self, timer, name, clock):
            self.timer = timer
            self.name = name
            self._clock = clock
            self._t0 = None

        def __enter__(self):
            if self._clock is not None:
                self._t0 = self._clock()
            return self

        def __exit__(self, *exc):
            if self._clock is not None:
                acc = self.timer._acc
                acc[self.name] = (acc.get(self.name, 0.0)
                                  + self._clock() - self._t0)
            return False

    def phase(self, name):
        from paddle_tpu import obs

        h = obs.handle()
        return self._Phase(self, name,
                           h.clock if h is not None else None)

    def phase_seconds(self):
        """Accumulated {phase: seconds} for the step in flight."""
        return dict(self._acc)

    def end_step(self):
        """Publish and reset the per-step accumulators; returns the
        step's {phase: seconds} (empty when obs is off)."""
        from paddle_tpu import obs

        out, self._acc = self._acc, {}
        h = obs.handle()
        if h is None:
            return {}
        self.steps += 1
        fam = h.registry.gauge("step_phase_seconds",
                               "Wall seconds per step phase",
                               labels=("program", "phase"))
        for ph in self.PHASES:
            if ph in out:
                fam.labels(program=self.program, phase=ph).set(out[ph])
        if out:
            h.tracer.counter("perf.step_phases", cat="perf",
                             **{ph: round(v, 6)
                                for ph, v in sorted(out.items())})
        compute = out.get("compute")
        if compute:
            rl = roofline(program_cost(self.program), compute)
            if rl is not None:
                _publish(h, self.program, rl)
                if (self.steps - 1) % HBM_SAMPLE_EVERY == 0:
                    sample_hbm(h)
        return out


def reset():
    """Clear module-level perf state (failed-cost memo, HBM sampling
    counters); tests call this alongside ``obs.reset``."""
    _hbm_calls.clear()
    _failed_cost.clear()
