"""Structured event log: an append-only JSON-lines journal of
lifecycle events, the queryable superset of the flight ring.

Where the :class:`~paddle_tpu.obs.flight.FlightRecorder` is a crash
black box (bounded ring, dumped only when something goes wrong), the
event log is the incident-reconstruction surface: every lifecycle
event — request admit/finish/fail, preemption, eviction, guardian
anomaly, checkpoint commit, jit trace, alert transitions — lands here
as one JSON object per line, with bounded file rotation so a
long-running replica never fills a disk.

Two inputs feed it:

- direct producers call :meth:`EventLog.log` (bracketed by the
  ``obs.event`` fault point so crash-during-journal is testable);
- every flight-recorder event is teed in via :meth:`EventLog.from_flight`
  (wired as the recorder's sink by ``obs.configure``), reusing the
  flight event's timestamp so the deterministic clock sequence seen by
  existing tests is unchanged.

The in-memory tail (``deque(maxlen=capacity)``) is always on; the file
journal only exists when a path is configured (``PT_OBS_EVENT_LOG`` or
``obs.configure(events_path=...)``).  Rotation is size-based:
``path`` -> ``path.1`` -> ... -> ``path.<max_files-1>``, oldest
dropped.  ``tools/obs_query.py`` reads the rotated set back in order.
"""
from __future__ import annotations

import json
import os
from collections import deque

#: every journal line carries at least these keys (schema gate in
#: tools/obs_dump.py checks them).
SCHEMA_KEYS = ("seq", "ts", "kind")


class EventLog:
    def __init__(self, clock, path=None, max_bytes=262144, max_files=3,
                 capacity=4096):
        self._clock = clock
        self.path = path
        self.max_bytes = int(max_bytes)
        self.max_files = int(max_files)
        if self.max_files < 1:
            raise ValueError(f"max_files must be >= 1, got {max_files}")
        self.capacity = int(capacity)
        self._tail = deque(maxlen=self.capacity)
        self.seq = 0                  # total events ever journaled
        self._file = None
        self._file_bytes = 0
        if path is not None:
            self._open()

    # -- producers ------------------------------------------------------

    def log(self, kind, **fields):
        """Journal one event; returns the event dict.  Bracketed by the
        ``obs.event`` fault point so a crash mid-journal is itself a
        testable failure mode."""
        from ..testing import faults

        faults.fire("obs.event", "before", path=self.path)
        ev = self._append(kind, round(self._clock(), 6), fields)
        faults.fire("obs.event", "after", path=self.path)
        return ev

    def from_flight(self, flight_ev):
        """Sink for the flight recorder: tee a ring event into the
        journal.  Reuses the flight event's timestamp (no extra clock
        read — the deterministic tick sequence is unchanged) and
        assigns the journal's own ``seq``."""
        fields = {k: v for k, v in flight_ev.items()
                  if k not in ("seq", "ts", "kind")}
        fields["flight_seq"] = flight_ev["seq"]
        self._append(flight_ev["kind"], flight_ev["ts"], fields)

    def _append(self, kind, ts, fields):
        self.seq += 1
        ev = {"seq": self.seq, "ts": ts, "kind": kind}
        ev.update(fields)
        self._tail.append(ev)
        if self._file is not None:
            line = json.dumps(ev, default=str) + "\n"
            data = line.encode()
            if self._file_bytes and \
                    self._file_bytes + len(data) > self.max_bytes:
                self._rotate()
            self._file.write(line)
            self._file.flush()
            self._file_bytes += len(data)
        return ev

    # -- file journal ---------------------------------------------------

    def _open(self):
        self._file = open(self.path, "a")
        self._file_bytes = os.path.getsize(self.path)

    def _rotate(self):
        self._file.close()
        oldest = f"{self.path}.{self.max_files - 1}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_files - 2, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if self.max_files > 1:
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        self._file = open(self.path, "a")
        self._file_bytes = 0

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    # -- consumers ------------------------------------------------------

    def events(self):
        """The in-memory tail, oldest-first."""
        return list(self._tail)

    def __len__(self):
        return len(self._tail)

    def journal_files(self):
        """Existing journal files, oldest rotation first, live file
        last — concatenation order for readers."""
        if self.path is None:
            return []
        paths = [f"{self.path}.{i}"
                 for i in range(self.max_files - 1, 0, -1)]
        paths.append(self.path)
        return [p for p in paths if os.path.exists(p)]


def journal_files(path, max_files=16):
    """Rotation set for ``path`` without a live :class:`EventLog` —
    oldest first (``path.N`` .. ``path.1``, then ``path``)."""
    paths = [f"{path}.{i}" for i in range(max_files, 0, -1)]
    paths.append(path)
    return [p for p in paths if os.path.exists(p)]


def read_journal(path, max_files=16):
    """Parse a journal (including rotated files) into event dicts,
    oldest-first."""
    out = []
    for p in journal_files(path, max_files=max_files):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


def match(ev, rid=None, kind=None, since=None, until=None):
    """One filter predicate shared by the CLI and tests.

    ``kind`` matches exactly or as a dotted prefix (``"req"`` matches
    ``"req.admit"``); ``since``/``until`` bound ``ts`` inclusively.
    """
    if rid is not None and ev.get("rid") != rid:
        return False
    if kind is not None:
        k = ev.get("kind", "")
        if k != kind and not k.startswith(kind + "."):
            return False
    if since is not None and ev.get("ts", 0.0) < since:
        return False
    if until is not None and ev.get("ts", 0.0) > until:
        return False
    return True


def query(events, rid=None, kind=None, since=None, until=None):
    """Filter an event iterable by rid / kind(-prefix) / time range."""
    return [ev for ev in events
            if match(ev, rid=rid, kind=kind, since=since, until=until)]
