"""Unified telemetry plane: metric registry + trace spans + flight
recorder, gated by ``PT_OBS={off,on}``.

One process-wide bundle (:func:`handle`) holds the three surfaces; the
whole layer is OFF by default and the off path is one cached ``None``
check per producer site — no allocation, no clock read, bit-identical
behavior (asserted by tests/test_obs.py's parity test).

Producer idiom (hot paths cache the handle)::

    from paddle_tpu import obs

    h = obs.handle()
    if h is not None:
        h.recorder.record("serve.preempt", rid=req.rid)
        h.registry.counter("serve_preemptions_total").inc()

    with obs.span("train.step", cat="train"):   # null ctx when off
        ...

Export surfaces:

- ``obs.handle().registry.prometheus_text()`` / ``.snapshot()``
- ``obs.handle().tracer.export_chrome(path)`` — Perfetto-viewable
- ``obs.dump(path)`` — flight-recorder JSON lines; crash paths
  (``GuardianAbort``, request failure) call :func:`auto_dump`, which
  also writes a file per dump under ``$PT_OBS_DUMP_DIR`` when set.

Tests swap the layer on/off in-process via :func:`configure`
(optionally with a deterministic :class:`LogicalClock`); ``reset()``
returns to the environment-driven default.
"""
from __future__ import annotations

import os
import threading

from .events import EventLog
from .flight import FlightRecorder
from .registry import MetricRegistry
from .trace import LogicalClock, Span, Tracer

__all__ = [
    "EventLog", "FlightRecorder", "LogicalClock", "MetricRegistry",
    "Span", "Tracer", "auto_dump", "beat", "configure", "dump",
    "enabled", "event", "handle", "instant", "perf", "reset", "span",
]

_MODES = ("off", "on")

_lock = threading.Lock()
_handle = None        # _Obs | None (None = telemetry off)
_initialized = False  # PT_OBS read yet?


class _Obs:
    """The live telemetry bundle: one clock feeding one registry, one
    tracer, one flight recorder, and one structured event log (the
    flight ring tees into the log), plus the health-plane state
    (heartbeats, SLO engines, ``/statusz`` providers, HTTP server)."""

    def __init__(self, clock=None, flight_capacity=512,
                 trace_capacity=65536, annotate=True, events_path=None,
                 events_max_bytes=262144, events_max_files=3,
                 events_capacity=4096):
        import time

        self.clock = clock if clock is not None else time.perf_counter
        self.registry = MetricRegistry()
        self.tracer = Tracer(clock=self.clock, capacity=trace_capacity,
                             annotate=annotate)
        if events_path is None:
            events_path = os.environ.get("PT_OBS_EVENT_LOG") or None
        self.events = EventLog(clock=self.clock, path=events_path,
                               max_bytes=events_max_bytes,
                               max_files=events_max_files,
                               capacity=events_capacity)
        self.recorder = FlightRecorder(clock=self.clock,
                                       capacity=flight_capacity,
                                       sink=self.events.from_flight)
        self.heartbeats = {}    # component -> last-beat timestamp
        self.slo_engines = []   # live health.SLOEngine instances
        self.statusz = {}       # provider name -> payload callable
        self.httpd = None
        port = os.environ.get("PT_OBS_HTTP")
        if port:
            from . import httpd as _httpd

            self.httpd = _httpd.ObsHTTPServer(port=int(port))

    def close(self):
        if self.httpd is not None:
            self.httpd.stop()
            self.httpd = None
        self.events.close()


def _env_mode():
    mode = os.environ.get("PT_OBS", "off").lower()
    if mode not in _MODES:
        raise ValueError(f"PT_OBS={mode!r}: expected off|on")
    return mode


def handle():
    """The live :class:`_Obs` bundle, or ``None`` when telemetry is
    off — the single branch every producer pays on the off path."""
    global _handle, _initialized
    if not _initialized:
        with _lock:
            if not _initialized:
                _handle = _Obs() if _env_mode() == "on" else None
                _initialized = True
    return _handle


def enabled():
    return handle() is not None


def configure(mode="on", clock=None, flight_capacity=512,
              trace_capacity=65536, annotate=True, events_path=None,
              events_max_bytes=262144, events_max_files=3,
              events_capacity=4096):
    """Programmatic gate (tests / bench A/B): rebuild the bundle
    regardless of ``PT_OBS``.  Returns the new handle (None for
    ``mode="off"``).  Producers that cached a handle at construction
    (EngineMetrics, Scheduler) keep the old one — reconfigure BEFORE
    building the objects under test."""
    global _handle, _initialized
    if mode not in _MODES:
        raise ValueError(f"obs.configure mode={mode!r}: expected off|on")
    with _lock:
        old = _handle
        _handle = (_Obs(clock=clock, flight_capacity=flight_capacity,
                        trace_capacity=trace_capacity, annotate=annotate,
                        events_path=events_path,
                        events_max_bytes=events_max_bytes,
                        events_max_files=events_max_files,
                        events_capacity=events_capacity)
                   if mode == "on" else None)
        _initialized = True
    if old is not None:
        old.close()
    return _handle


def reset():
    """Drop all telemetry state; the next :func:`handle` re-reads
    ``PT_OBS``."""
    global _handle, _initialized
    with _lock:
        old = _handle
        _handle = None
        _initialized = False
    if old is not None:
        old.close()
    perf.reset()


# -- thin producer helpers (no-ops when off) ----------------------------

class _NullSpan:
    """Stands in for a live span when telemetry is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kv):
        return self


NULL_SPAN = _NullSpan()


def span(name, cat="host", trace_id=None, **args):
    h = handle()
    if h is None:
        return NULL_SPAN
    return h.tracer.span(name, cat=cat, trace_id=trace_id, **args)


def instant(name, cat="host", trace_id=None, **args):
    h = handle()
    if h is not None:
        h.tracer.instant(name, cat=cat, trace_id=trace_id, **args)


def event(kind, **fields):
    h = handle()
    if h is not None:
        h.recorder.record(kind, **fields)


def beat(name, now=None):
    """Heartbeat for ``/healthz`` staleness: stamp component ``name``
    as alive.  Hot loops pass ``now`` (a timestamp they already read)
    to avoid an extra clock read."""
    h = handle()
    if h is not None:
        h.heartbeats[name] = h.clock() if now is None else now


def dump(path=None, reason="manual"):
    """Explicit flight-recorder dump; returns the JSON-lines text, or
    ``None`` when telemetry is off."""
    h = handle()
    if h is None:
        return None
    return h.recorder.dump(path=path, reason=reason)


def auto_dump(reason, extra=None):
    """Crash-path dump (GuardianAbort, request failure).  Keeps the
    text on ``recorder.last_dump``; additionally writes one file per
    dump under ``$PT_OBS_DUMP_DIR`` when that is set."""
    h = handle()
    if h is None:
        return None
    path = None
    dump_dir = os.environ.get("PT_OBS_DUMP_DIR")
    if dump_dir:
        os.makedirs(dump_dir, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_." else "-"
                       for c in reason)
        path = os.path.join(dump_dir,
                            f"flight-{h.recorder.dumps}-{safe}.jsonl")
    return h.recorder.dump(path=path, reason=reason, extra=extra)


from . import perf  # noqa: E402,F401  (imports obs lazily; keep last)
