"""Functional bridge: Layer -> pure function over a parameter pytree.

This is what lets one model implementation serve both execution modes the
reference maintains separately (dygraph vs static graph): the same eager
Layer code is traced under jax with its parameters swapped for tracers.

Reference analog: ``paddle/fluid/eager`` dygraph vs the jit/static path —
here unified because eager ops are already jax calls.
"""
from __future__ import annotations

import jax

from ..autograd import engine
from ..core.tensor import Tensor


def param_tree(layer, trainable_only=True):
    """{name: jax array} for the layer's parameters."""
    out = {}
    for name, p in layer.named_parameters():
        if trainable_only and not p.trainable:
            continue
        out[name] = p._data
    return out


def load_param_tree(layer, tree):
    named = dict(layer.named_parameters())
    for name, arr in tree.items():
        named[name]._data = arr


def functional_call(layer, params, *args, **kwargs):
    """Call layer.forward with parameter values taken from ``params``
    (a {name: array} tree), without mutating the layer afterwards.
    Returns raw jax arrays (pytree). Grad recording is disabled — use
    jax.grad over this function for derivatives."""
    named = dict(layer.named_parameters())
    saved = []
    try:
        for k, v in params.items():
            t = named[k]
            saved.append((t, t._data))
            t._data = v
        wrapped = [Tensor(a) if not isinstance(a, Tensor) and a is not None
                   else a for a in args]
        with engine.no_grad():
            out = layer(*wrapped, **kwargs)
        return jax.tree.map(
            lambda o: o._data if isinstance(o, Tensor) else o, out,
            is_leaf=lambda x: isinstance(x, Tensor))
    finally:
        for t, d in saved:
            t._data = d
