"""Automatic control-flow conversion for ``to_static``.

Reference: ``python/paddle/jit/dy2static/program_translator.py:1714``
(StaticFunction AST path) + ``dy2static/transformers/`` (IfElse / Loop
transformers) — there, Python source is transpiled so that ``if``/``while``
over tensors become ``cond``/``while_loop`` layers before tracing.

TPU-native re-design: the same source-to-source transform, but the emitted
runtime calls (``_dy2st_if`` / ``_dy2st_while``) dispatch *dynamically* —
a concrete (eager) condition runs plain Python, a traced condition lowers
onto ``jax.lax.cond`` / ``lax.while_loop`` via ``static.nn``.  One
transformed body therefore serves both dygraph and the jit trace, which is
exactly the contract the reference's convert_ifelse/convert_while_loop
helpers implement (``dy2static/convert_operators.py:40``).

Coverage: ``if``/``elif``/``else`` (including both-branches-return),
``while``, and ``for _ in range(...)``.  Statements that cannot be lifted
into functional control flow (``break``/``continue`` under a traced
condition, one-armed returns) keep Python semantics and surface through
the existing graph-break fallback — the reference behaves the same way
through SOT's subgraph fallback.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap


class _Undef:
    """UndefinedVar analog (reference dy2static/utils.py UndefinedVar):
    placeholder for names bound in only one branch; any real use raises."""

    __slots__ = ("name",)

    def __init__(self, name="<var>"):
        self.name = name

    def _die(self, *a, **k):
        raise UnboundLocalError(
            f"variable {self.name!r} was only assigned on one branch of a "
            "converted if/while and is read on a path where it is unbound")

    __bool__ = __call__ = __getitem__ = __add__ = __radd__ = _die
    __mul__ = __sub__ = __getattr__ = _die


def _is_traced(x):
    import jax

    from ..core.tensor import Tensor

    d = x._data if isinstance(x, Tensor) else x
    return isinstance(d, jax.core.Tracer)


def _to_bool(x):
    from ..core.tensor import Tensor

    return bool(x._data if isinstance(x, Tensor) else x)


def _dy2st_if(cond, true_fn, false_fn, vals):
    """convert_ifelse analog (convert_operators.py:40): traced condition
    -> lax.cond through static.nn; concrete -> plain Python."""
    if _is_traced(cond):
        from ..static import nn as static_nn

        return static_nn.cond(cond, lambda: true_fn(*vals),
                              lambda: false_fn(*vals))
    return true_fn(*vals) if _to_bool(cond) else false_fn(*vals)


def _dy2st_while(cond_fn, body_fn, vals):
    """convert_while_loop analog: a traced condition lowers the whole
    loop onto lax.while_loop; otherwise plain Python iteration."""
    vals = tuple(vals)
    c = cond_fn(*vals)
    if _is_traced(c) or any(_is_traced(v) for v in vals
                            if not isinstance(v, _Undef)):
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..static import nn as static_nn

        if any(isinstance(v, _Undef) for v in vals):
            # Vars first bound INSIDE the body (e.g. the for-loop target):
            # probe the body's output avals to materialize a typed initial
            # carry (the reference fills UndefinedVar slots the same way).
            import jax as _jax

            def _unwrap(v):
                return v._data if isinstance(v, Tensor) else v

            probe = [jnp.zeros((), jnp.int32) if isinstance(v, _Undef)
                     else _unwrap(v) for v in vals]
            try:
                avals = _jax.eval_shape(
                    lambda *vs: tuple(_unwrap(o) for o in
                                      body_fn(*[Tensor(jnp.asarray(x))
                                                for x in vs])), *probe)
            except Exception as e:
                bad = [v.name for v in vals if isinstance(v, _Undef)]
                raise UnboundLocalError(
                    f"converted while loop carries unbound variables "
                    f"{bad} into a traced lowering and the body reads "
                    "them before assigning") from e
            vals = tuple(
                Tensor(jnp.zeros(a.shape, a.dtype))
                if isinstance(v, _Undef) else v
                for v, a in zip(vals, avals))
        # Loop carries must be arrays with stable dtype: promote python
        # scalars once so `i = 0; while i < n: i += 1` lowers cleanly.
        carry = [v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
                 for v in vals]
        out = static_nn.while_loop(cond_fn, lambda *vs: tuple(body_fn(*vs)),
                                   carry)
        return tuple(out)
    while _to_bool(c):
        vals = tuple(body_fn(*vals))
        c = cond_fn(*vals)
    return vals


class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list (assignment/augassign/for/with
    targets) — the candidate outputs of a converted branch/loop body."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # don't descend: inner scope

    def visit_AsyncFunctionDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_ClassDef(self, node):
        self.names.add(node.name)


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _LoadedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _loaded(node_or_list):
    v = _LoadedNames()
    for n in (node_or_list if isinstance(node_or_list, list)
              else [node_or_list]):
        v.visit(n)
    return v.names


def _contains(stmts, *types):
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, types):
                return True
    return False


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _tuple_of(names, ctx=None):
    return ast.Tuple(elts=[_name(n, ctx or ast.Load()) for n in names],
                     ctx=ctx or ast.Load())


def _localfix(names):
    """`x = locals().get('x', _Undef('x'))` pre-bindings: makes every
    captured name referenceable whether or not it is bound yet (the
    reference inserts UndefinedVar assignments the same way)."""
    out = []
    for n in sorted(names):
        call = ast.Call(
            func=ast.Attribute(
                value=ast.Call(func=_name("locals"), args=[], keywords=[]),
                attr="get", ctx=ast.Load()),
            args=[ast.Constant(n),
                  ast.Call(func=_name("_dy2st_undef_cls"),
                           args=[ast.Constant(n)], keywords=[])],
            keywords=[])
        out.append(ast.Assign(targets=[_name(n, ast.Store())], value=call))
    return out


class ControlFlowTransformer(ast.NodeTransformer):
    """IfElseTransformer + LoopTransformer analog
    (dy2static/transformers/ifelse_transformer.py, loop_transformer.py)."""

    def __init__(self, local_names=None):
        self._n = 0
        self.converted = 0
        # the function's local-name universe: only these may become branch
        # parameters (a global like `paddle` or `F` must resolve through
        # the generated functions' enclosing scope, never be shadowed)
        self._locals = set(local_names or ())

    def _only_locals(self, names):
        if not self._locals:
            return sorted(names)
        return sorted(set(names) & self._locals)

    def _uid(self, kind):
        self._n += 1
        return f"__dy2st_{kind}_{self._n}"

    # -- if/else ------------------------------------------------------------

    def visit_If(self, node):
        self.generic_visit(node)
        # Unsupported shapes keep Python semantics (graph-break fallback).
        if _contains([node], ast.Break, ast.Continue, ast.Yield,
                     ast.YieldFrom):
            return node
        body_ret = any(isinstance(s, ast.Return) for s in node.body)
        else_ret = any(isinstance(s, ast.Return) for s in node.orelse)
        if body_ret or else_ret:
            # liftable only when BOTH arms end in a return (then the
            # whole statement becomes `return _dy2st_if(...)`)
            if not (node.body and node.orelse
                    and isinstance(node.body[-1], ast.Return)
                    and isinstance(node.orelse[-1], ast.Return)
                    and not _contains(node.body[:-1], ast.Return)
                    and not _contains(node.orelse[:-1], ast.Return)):
                return node
            return self._convert_returning_if(node)
        return self._convert_assigning_if(node)

    def _branch_fn(self, fname, params, stmts, result_names):
        ret = ast.Return(value=_tuple_of(result_names))
        return ast.FunctionDef(
            name=fname,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=list(stmts) + [ret], decorator_list=[], returns=None)

    def _convert_assigning_if(self, node):
        out_names = self._only_locals(
            _assigned(node.body) | _assigned(node.orelse))
        if not out_names:
            # side-effect-only branches (e.g. list.append): keep Python
            return node
        params = self._only_locals(
            (_loaded(node.body) | _loaded(node.orelse)) | set(out_names))
        params = sorted(set(params) | set(out_names))
        tname, fname = self._uid("true"), self._uid("false")
        tfn = self._branch_fn(tname, params, node.body, out_names)
        ffn = self._branch_fn(fname, params, node.orelse or [ast.Pass()],
                              out_names)
        call = ast.Call(
            func=_name("_dy2st_if"),
            args=[node.test, _name(tname), _name(fname),
                  _tuple_of(params)],
            keywords=[])
        assign = ast.Assign(targets=[_tuple_of(out_names, ast.Store())],
                            value=call)
        self.converted += 1
        return _localfix(params) + [tfn, ffn, assign]

    def _convert_returning_if(self, node):
        params = self._only_locals(_loaded(node.body) | _loaded(node.orelse)
                                   | _loaded(node.test))
        tname, fname = self._uid("true"), self._uid("false")

        def as_fn(fname_, stmts):
            last = stmts[-1]
            body = list(stmts[:-1]) + [ast.Return(
                value=last.value if last.value is not None
                else ast.Constant(None))]
            return ast.FunctionDef(
                name=fname_,
                args=ast.arguments(
                    posonlyargs=[], args=[ast.arg(arg=p) for p in params],
                    kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=body, decorator_list=[], returns=None)

        tfn = as_fn(tname, node.body)
        ffn = as_fn(fname, node.orelse)
        call = ast.Call(
            func=_name("_dy2st_if"),
            args=[node.test, _name(tname), _name(fname),
                  _tuple_of(params)],
            keywords=[])
        self.converted += 1
        return _localfix(params) + [tfn, ffn, ast.Return(value=call)]

    # -- while --------------------------------------------------------------

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _contains([node], ast.Break, ast.Continue,
                                    ast.Return, ast.Yield, ast.YieldFrom):
            return node
        assigned_in_body = _assigned(node.body)
        carried = self._only_locals(assigned_in_body | _loaded(node.test))
        # generated loaded-only temps (range stop/step) stay closed-over;
        # a generated counter IS loop state and must be carried
        carried = [n for n in carried
                   if not n.startswith("__dy2st") or n in assigned_in_body]
        if not carried:
            return node
        cname, bname = self._uid("cond"), self._uid("body")
        cond_fn = ast.FunctionDef(
            name=cname,
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=p) for p in carried],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None)
        body_fn = self._branch_fn(bname, carried, node.body, carried)
        call = ast.Call(
            func=_name("_dy2st_while"),
            args=[_name(cname), _name(bname), _tuple_of(carried)],
            keywords=[])
        assign = ast.Assign(targets=[_tuple_of(carried, ast.Store())],
                            value=call)
        self.converted += 1
        return _localfix(carried) + [cond_fn, body_fn, assign]

    # -- for over range -----------------------------------------------------

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or not isinstance(node.target, ast.Name):
            return node
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and 1 <= len(it.args) <= 3
                and not it.keywords):
            return node
        if _contains([node], ast.Break, ast.Continue, ast.Return,
                     ast.Yield, ast.YieldFrom):
            return node
        # for i in range(a[,b[,c]]): body  ->  hidden counter k:
        #   k = a0
        #   while (b0 - k) * c0 > 0:   # sign-correct for any step
        #       i = k; body; k += c0
        # i is assigned INSIDE the body so its post-loop value matches
        # Python's for semantics (last iterated value, not one past).
        i = node.target.id
        if len(it.args) == 1:
            start, stop, step = ast.Constant(0), it.args[0], ast.Constant(1)
        elif len(it.args) == 2:
            start, stop, step = it.args[0], it.args[1], ast.Constant(1)
        else:
            start, stop, step = it.args
        stop_name = self._uid("stop")
        step_name = self._uid("step")
        k = self._uid("iter")
        pre = [
            ast.Assign(targets=[_name(stop_name, ast.Store())], value=stop),
            ast.Assign(targets=[_name(step_name, ast.Store())], value=step),
            ast.Assign(targets=[_name(k, ast.Store())], value=start),
        ]
        test = ast.Compare(
            left=ast.BinOp(
                left=ast.BinOp(left=_name(stop_name), op=ast.Sub(),
                               right=_name(k)),
                op=ast.Mult(), right=_name(step_name)),
            ops=[ast.Gt()], comparators=[ast.Constant(0)])
        body = ([ast.Assign(targets=[_name(i, ast.Store())],
                            value=_name(k))]
                + list(node.body)
                + [ast.AugAssign(target=_name(k, ast.Store()),
                                 op=ast.Add(), value=_name(step_name))])
        while_node = ast.While(test=test, body=body, orelse=[])
        out = pre + [while_node]
        # the generated counter is loop state: admit it to the local
        # universe so the while conversion carries it
        self._locals.add(k)
        # re-run the while conversion on the rewritten loop
        converted = self.visit_While(while_node)
        if isinstance(converted, list):
            out = pre + converted
        self.converted += 1
        return out


def convert_to_static(fn):
    """Transpile ``fn``'s source so tensor-driven if/while/for lower onto
    lax control flow (reference program_translator.py:1714).  Returns
    (converted_fn, n_converted); (fn, 0) when nothing needed conversion
    or the source is unavailable."""
    try:
        raw_fn = fn.__func__ if inspect.ismethod(fn) else fn
        src = textwrap.dedent(inspect.getsource(raw_fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn, 0
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn, 0
    fdef.decorator_list = []  # the wrapper re-applies semantics
    # local-name universe: code-object locals + anything assigned in source
    local_names = set(raw_fn.__code__.co_varnames) \
        | set(raw_fn.__code__.co_cellvars) | _assigned(fdef.body)
    local_names |= {a.arg for a in fdef.args.args}
    tr = ControlFlowTransformer(local_names)
    tr.visit(tree)
    if not tr.converted:
        return fn, 0
    ast.fix_missing_locations(tree)
    glb = dict(raw_fn.__globals__)
    glb["_dy2st_if"] = _dy2st_if
    glb["_dy2st_while"] = _dy2st_while
    glb["_dy2st_undef_cls"] = _Undef
    if raw_fn.__closure__:
        # re-expose free variables by value (reference's closure capture)
        for name, cell in zip(raw_fn.__code__.co_freevars,
                              raw_fn.__closure__):
            try:
                glb.setdefault(name, cell.cell_contents)
            except ValueError:
                pass
    try:
        code = compile(tree, filename=f"<dy2static {raw_fn.__name__}>",
                       mode="exec")
        ns = {}
        exec(code, glb, ns)
        new_fn = ns[fdef.name]
    except Exception:
        return fn, 0
    new_fn.__defaults__ = raw_fn.__defaults__
    new_fn.__kwdefaults__ = raw_fn.__kwdefaults__
    functools.update_wrapper(new_fn, raw_fn)
    new_fn.__dy2static_source__ = ast.unparse(tree)
    if inspect.ismethod(fn):
        new_fn = new_fn.__get__(fn.__self__)
    return new_fn, tr.converted
