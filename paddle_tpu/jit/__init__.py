"""paddle.jit analog — dygraph-to-static via tracing.

Reference: ``python/paddle/jit/`` (``to_static`` at api.py:197; SOT bytecode
path + AST path).  TPU-native re-design: because every eager op runs through
jax, a Layer's forward *is already traceable* — ``to_static`` lifts it into
a pure function over (parameters, buffers, inputs) and ``jax.jit``s it, with
a signature cache keyed on input shapes/dtypes + static args (the analog of
SOT's guard cache, sot/opcode_translator).  Buffer mutation (BN running
stats) is functionalized: the traced function returns updated buffer values
which are written back after each call.

``jit.save``/``jit.load`` serialize the lowered StableHLO text + params
(the TranslatedLayer analog).
"""
from __future__ import annotations

import functools
import os
import pickle

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd import engine
from ..core.tensor import Tensor
from ..nn.layers import Layer


class _Guard:
    """Cache key: pytree structure + shapes/dtypes of tensor leaves +
    values of non-tensor leaves (SOT guard analog)."""

    @staticmethod
    def key(args, kwargs):
        leaves, treedef = jax.tree.flatten((args, kwargs),
                                           is_leaf=lambda x: isinstance(
                                               x, Tensor))
        sig = []
        for leaf in leaves:
            if isinstance(leaf, Tensor):
                sig.append(("T", tuple(leaf.shape), str(leaf.dtype)))
            else:
                try:
                    hash(leaf)
                    sig.append(("S", leaf))
                except TypeError:
                    sig.append(("S", repr(leaf)))
        return treedef, tuple(sig)


_TO_STATIC_ENABLED = True


def enable_to_static(flag: bool) -> None:
    """Global dygraph/static switch (reference
    ``python/paddle/jit/api.py`` enable_to_static / ProgramTranslator
    ``enable``): False makes every StaticFunction run its original
    eager body."""
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(flag)


# jax error types that mean "the traced python needed a concrete value"
# — i.e. data-dependent control flow the whole-graph trace can't honor.
_BREAK_ERRORS = (
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.ConcretizationTypeError,
)

_FALLBACK = object()  # cache sentinel: this guard key runs eagerly


class StaticFunction:
    def __init__(self, function, layer=None, input_spec=None,
                 full_graph=True, remat=False):
        self._fn = function
        self._layer = layer
        self._input_spec = input_spec
        self._full_graph = full_graph
        self._remat = remat  # jax.checkpoint the traced body
        self._cache = {}
        self._warned_break = False
        self._converted = None  # lazily AST-converted body (dy2static)
        self._n_converted = 0
        functools.update_wrapper(self, function)

    def _traced_fn(self):
        """The function the whole-graph trace runs: the AST-converted
        body when the source has tensor-driven control flow (reference
        program_translator.py:1714 AST path), else the original."""
        if self._converted is None:
            from .dy2static import convert_to_static

            self._converted, self._n_converted = convert_to_static(
                self._fn)
        return self._converted

    def _state_tensors(self):
        if self._layer is None:
            return []
        tensors = [p for _, p in self._layer.named_parameters()]
        tensors += [b for _, b in self._layer.named_buffers()]
        return tensors

    def _graph_break(self, key, err):
        """Record the SOT-analog decision: this guard key cannot be one
        whole graph (data-dependent python control flow), so it executes
        eagerly — each registry op is still its own cached XLA program,
        the TPU analog of SOT's per-segment subgraphs
        (reference program_translator.py:711 fallback)."""
        if self._full_graph:
            raise RuntimeError(
                "to_static(full_graph=True): the traced function needs a "
                "concrete tensor value for python control flow "
                f"({type(err).__name__}). Rewrite with paddle.where/"
                "lax.cond-style ops, or use full_graph=False to let this "
                "call site fall back to eager per-op execution.") from err
        self._cache[key] = _FALLBACK
        if not self._warned_break:
            self._warned_break = True
            import warnings

            warnings.warn(
                f"to_static: graph break in "
                f"{getattr(self._fn, '__qualname__', self._fn)} — "
                f"{type(err).__name__}: a tensor value drives python "
                "control flow. Falling back to eager per-op execution "
                "for this input signature (per-op XLA programs stay "
                "jit-cached). Use jax-style ops (paddle.where, masking) "
                "to recover whole-graph compilation.",
                stacklevel=3)

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED:
            return self._fn(*args, **kwargs)
        state = self._state_tensors()
        key = _Guard.key(args, kwargs)
        entry = self._cache.get(key)
        if entry is _FALLBACK:
            return self._fn(*args, **kwargs)
        if entry is None:
            entry = self._compile(args, kwargs, state)
            self._cache[key] = entry
        jitted = entry

        leaves, _ = jax.tree.flatten((args, kwargs),
                                     is_leaf=lambda x: isinstance(x, Tensor))
        tensor_leaves = [x for x in leaves if isinstance(x, Tensor)]
        sdatas = [t._data for t in state]
        idatas = [t._data for t in tensor_leaves]

        # Training path: build the autograd graph THROUGH the jitted call
        # (reference to_static fully supports training — jit/api.py:197);
        # a grad-recording forward uses jax.vjp over the compiled function
        # and hangs a vjp-fallback GradNode off the outputs.
        all_inputs = list(state) + tensor_leaves
        diff_idx = [i for i, t in enumerate(all_inputs)
                    if not t.stop_gradient]
        need_grad = engine.is_grad_enabled() and bool(diff_idx)

        if not need_grad:
            try:
                out_datas, new_state = jitted(sdatas, idatas)
            except _BREAK_ERRORS as e:
                self._graph_break(key, e)
                return self._fn(*args, **kwargs)
            for t, d in zip(state, new_state):
                t._data = d
            return jax.tree.map(
                lambda d: Tensor(d) if d is not None else None, out_datas)

        # vjp only over the grad-requiring leaves (non-diff ones are closed
        # over, registry._close_over style) — no wasted backward compute
        # for frozen parameters.
        all_datas = sdatas + idatas
        n_state = len(sdatas)

        def f(*diff_datas):
            full = list(all_datas)
            for i, d in zip(diff_idx, diff_datas):
                full[i] = d
            return jitted(full[:n_state], full[n_state:])

        try:
            out_datas, vjp_fn, new_state = jax.vjp(
                f, *[all_datas[i] for i in diff_idx], has_aux=True)
        except _BREAK_ERRORS as e:
            self._graph_break(key, e)
            return self._fn(*args, **kwargs)
        for t, d in zip(state, new_state):
            t._data = d

        out_flat, out_tree = jax.tree.flatten(out_datas)

        def vjp_saved(cotangent):
            cots = (list(cotangent) if isinstance(cotangent, tuple)
                    else [cotangent])
            # Integer/bool outputs take float0 cotangents (jax.vjp
            # contract), not the engine's dtype-matched zeros.
            cots = [np.zeros(np.shape(p), jax.dtypes.float0)
                    if not jnp.issubdtype(p.dtype, jnp.inexact) else c
                    for c, p in zip(cots, out_flat)]
            return list(vjp_fn(jax.tree.unflatten(out_tree, cots)))

        node = engine.GradNode(None, vjp_saved, all_inputs, {},
                               vjp_fallback=True, diff_idx=diff_idx)
        outs = [Tensor(d, stop_gradient=not jnp.issubdtype(
            d.dtype, jnp.inexact)) for d in out_flat]
        node.bind_outputs(outs)
        return jax.tree.unflatten(out_tree, outs)

    def _compile(self, args, kwargs, state):
        fn = self._traced_fn()
        treedef, _ = _Guard.key(args, kwargs)
        leaves, _ = jax.tree.flatten((args, kwargs),
                                     is_leaf=lambda x: isinstance(x, Tensor))
        is_tensor = [isinstance(x, Tensor) for x in leaves]
        static_leaves = [None if t else x
                         for t, x in zip(is_tensor, leaves)]

        def pure(state_datas, input_datas):
            saved = [t._data for t in state]
            it = iter(input_datas)
            rebuilt = [Tensor(next(it)) if t else s
                       for t, s in zip(is_tensor, static_leaves)]
            new_args, new_kwargs = jax.tree.unflatten(treedef, rebuilt)
            try:
                for t, d in zip(state, state_datas):
                    t._data = d
                with engine.no_grad():
                    out = fn(*new_args, **new_kwargs)
                out_datas = jax.tree.map(
                    lambda o: o._data if isinstance(o, Tensor) else o, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                new_state = [t._data for t in state]
            finally:
                for t, d in zip(state, saved):
                    t._data = d
            return out_datas, new_state

        if self._remat:
            # recompute semantics: only the inputs are saved; the body
            # reruns in the backward (fleet.utils.recompute rides this)
            return jax.jit(jax.checkpoint(pure))
        return jax.jit(pure)

    # Reference API parity.
    @property
    def code(self):
        """The traced source (reference StaticFunction.code returns the
        dy2static-transformed source)."""
        import inspect

        fn = self._traced_fn()
        src = getattr(fn, "__dy2static_source__", None)
        if src:
            return src
        try:
            return inspect.getsource(
                fn.__func__ if inspect.ismethod(fn) else fn)
        except (OSError, TypeError):
            return "<compiled by paddle_tpu.jit (XLA)>"

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    def rollback(self):
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, **kwargs):
    """Reference: python/paddle/jit/api.py:197.  Like the reference's
    default SOT path, ``full_graph=False`` allows graph breaks: a call
    site whose trace needs concrete tensor values falls back to eager
    per-op execution (warned once); ``full_graph=True`` raises instead
    (the reference's AST whole-graph contract)."""

    def decorate(fn):
        if getattr(fn, "_not_to_static", False):
            return fn
        if isinstance(fn, Layer):
            if getattr(fn.forward, "_not_to_static", False):
                return fn
            sf = StaticFunction(fn.forward, layer=fn,
                                input_spec=input_spec,
                                full_graph=full_graph)
            fn.forward = sf
            return fn
        layer = getattr(fn, "__self__", None)
        layer = layer if isinstance(layer, Layer) else None
        return StaticFunction(fn, layer=layer, input_spec=input_spec,
                              full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class InputSpec:
    """Reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def save(layer, path, input_spec=None, **configs):
    """Serialize params + (when given input_spec) the compiled program.

    Two program forms are stored (TranslatedLayer analog —
    ``python/paddle/jit/translated_layer.py``):

    - ``stablehlo``: the lowered module text, for inspection/tooling;
    - ``exported``: ``jax.export`` bytes of the forward with the weights
      baked in as constants — executable after load with NO python model
      code (the reference Predictor's "inference from artifact alone",
      ``analysis_predictor.h:105``).  Exported multi-platform
      (cpu+current) when every traced op allows it, else current
      platform only (e.g. Pallas kernels are TPU-only custom calls).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {}
    if isinstance(layer, Layer):
        for name, p in layer.state_dict().items():
            state[name] = np.asarray(p._data)
    payload = {"state_dict": state, "format": "paddle_tpu.jit.v1"}
    if input_spec:
        try:
            datas = [np.zeros(s.shape, s.dtype) if isinstance(s, InputSpec)
                     else np.asarray(s._data) for s in input_spec]
            fn = layer.forward if isinstance(layer, Layer) else layer

            def pure(*xs):
                with engine.no_grad():
                    out = fn(*[Tensor(x) for x in xs])
                return jax.tree.map(
                    lambda o: o._data if isinstance(o, Tensor) else o, out,
                    is_leaf=lambda x: isinstance(x, Tensor))

            with jax.enable_x64(False):
                jitted = jax.jit(pure)
                lowered = jitted.lower(*datas)
                payload["stablehlo"] = lowered.as_text()
                from jax import export as _export

                current = jax.devices()[0].platform
                plats = ([current] if current == "cpu"
                         else ["cpu", current])
                avals = [jax.ShapeDtypeStruct(d.shape, d.dtype)
                         for d in datas]
                try:
                    exp = _export.export(jitted, platforms=plats)(*avals)
                except Exception:
                    # Platform-specific custom calls (Pallas) can't lower
                    # cross-platform; keep the current platform only.
                    exp = _export.export(jitted)(*avals)
                payload["exported"] = exp.serialize()
        except Exception as e:
            # Do not silently ship a checkpoint without the program the
            # caller asked for (input_spec given == lowering requested).
            raise RuntimeError(
                f"jit.save: lowering to StableHLO failed: {e}") from e
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(payload, f)


class TranslatedLayer(Layer):
    """A loaded artifact: weights + (when saved with input_spec) the
    executable program.  ``forward`` runs the deserialized program —
    no python model class required (reference translated_layer.py)."""

    def __init__(self, payload):
        super().__init__()
        self._payload = payload
        self._state = {k: Tensor(v) for k, v in
                       payload["state_dict"].items()}
        self._exported = None

    def state_dict(self, *a, **k):
        return dict(self._state)

    def program(self):
        return self._payload.get("stablehlo", "")

    def has_program(self):
        return "exported" in self._payload

    def _exp(self):
        if self._exported is None:
            from jax import export as _export

            self._exported = _export.deserialize(
                self._payload["exported"])
        return self._exported

    def forward(self, *inputs):
        if not self.has_program():
            raise RuntimeError(
                "this artifact was saved without input_spec — no program "
                "was lowered; rebuild the model and set_state_dict, or "
                "re-save with input_spec")
        exp = self._exp()
        datas = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                 for x in inputs]
        # Match the exported avals (the artifact was traced x64-off).
        datas = [jnp.asarray(d, aval.dtype)
                 for d, aval in zip(datas, exp.in_avals)]
        with jax.enable_x64(False):
            out = exp.call(*datas)
        return jax.tree.map(lambda o: Tensor(o), out)


def load(path, **configs):
    with open(path + ".pdparams", "rb") as f:
        payload = pickle.load(f)
    return TranslatedLayer(payload)


# --- dy2static logging / module-ignore surface -----------------------------
# Reference: python/paddle/jit/api.py:144 (ignore_module),
# python/paddle/jit/dy2static/logging_utils.py (set_code_level,
# set_verbosity).  The ignore set is consulted by the AST control-flow
# converter (jit/dy2static.py): functions defined in ignored modules are
# never rewritten.
_IGNORED_MODULES: set = set()
_VERBOSITY = 0
_CODE_LEVEL = -1


def ignore_module(modules):
    """Exempt ``modules`` (list of module objects) from dynamic-to-static
    conversion (reference jit/api.py:144)."""
    for m in modules:
        _IGNORED_MODULES.add(getattr(m, "__name__", str(m)))


def set_verbosity(level=0, also_to_stdout=False):
    """Set dy2static log verbosity (reference
    jit/dy2static/logging_utils.py)."""
    global _VERBOSITY
    _VERBOSITY = int(level)
    import logging

    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level > 0 else logging.WARNING)


def set_code_level(level=100, also_to_stdout=False):
    """Set which transformed-code stage gets logged (reference
    jit/dy2static/logging_utils.py)."""
    global _CODE_LEVEL
    _CODE_LEVEL = int(level)
