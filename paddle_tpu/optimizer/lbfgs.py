"""L-BFGS optimizer with strong-Wolfe line search.

Reference: ``python/paddle/optimizer/lbfgs.py:120`` (LBFGS; Nocedal &
Wright Algorithm 7.5 two-loop recursion, strong-Wolfe cubic line
search).

TPU-native split: the *closure* (loss + grads) runs on device through
the normal eager/compiled path; the curvature bookkeeping — two-loop
recursion over the (s, y) history, Wolfe bracketing — is tiny
O(history * n) vector math, driven host-side exactly like the
reference's dygraph implementation (it is inherently sequential, with
data-dependent termination that cannot usefully live under jit).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .optimizer import Optimizer


def _gather_flat(params, attr):
    outs = []
    for p in params:
        if attr == "data":
            outs.append(np.asarray(p._data, np.float64).ravel())
        else:
            g = p.grad
            outs.append(np.zeros(int(np.prod(p.shape)))
                        if g is None
                        else np.asarray(g._data, np.float64).ravel())
    return np.concatenate(outs) if outs else np.zeros(0)


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    # reference lbfgs.py _cubic_interpolate (same formula both repos
    # cite from Nocedal & Wright eq. 3.59).
    if bounds is not None:
        xmin_bound, xmax_bound = bounds
    else:
        xmin_bound, xmax_bound = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1 * d1 - g1 * g2
    if d2_square >= 0:
        d2 = np.sqrt(d2_square)
        if x1 <= x2:
            min_pos = x2 - (x2 - x1) * ((g2 + d2 - d1)
                                        / (g2 - g1 + 2 * d2))
        else:
            min_pos = x1 - (x1 - x2) * ((g1 + d2 - d1)
                                        / (g1 - g2 + 2 * d2))
        return min(max(min_pos, xmin_bound), xmax_bound)
    return (xmin_bound + xmax_bound) / 2.0


class LBFGS(Optimizer):
    """L-BFGS (reference optimizer/lbfgs.py:120).  ``step`` takes a
    closure re-evaluating the loss with gradients."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        if max_eval is None:
            max_eval = max_iter * 5 // 4
        super().__init__(learning_rate=learning_rate,
                         parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)
        self.max_iter = int(max_iter)
        self.max_eval = int(max_eval)
        self.tolerance_grad = float(tolerance_grad)
        self.tolerance_change = float(tolerance_change)
        self.history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("only 'strong_wolfe' is supported")
        self.line_search_fn = line_search_fn
        self._state = {"func_evals": 0, "n_iter": 0,
                       "old_sks": [], "old_yks": [], "ro": [],
                       "d": None, "t": None, "prev_flat_grad": None,
                       "H_diag": 1.0}

    # -- flat param io -----------------------------------------------------
    def _set_flat(self, flat):
        offset = 0
        for p in self._parameter_list():
            n = int(np.prod(p.shape)) if p.shape else 1
            chunk = flat[offset:offset + n].reshape(tuple(p.shape))
            p._data = jnp.asarray(chunk, p._data.dtype)
            offset += n

    def _directional_evaluate(self, closure, x, t, d):
        self._set_flat(x + t * d)
        loss = float(closure())
        flat_grad = _gather_flat(self._parameter_list(), "grad")
        self._state["func_evals"] += 1
        return loss, flat_grad

    # -- strong wolfe (reference _strong_wolfe) ----------------------------
    def _strong_wolfe(self, closure, x, t, d, f, g, gtd,
                      c1=1e-4, c2=0.9, tolerance_change=1e-9,
                      max_ls=25):
        d_norm = np.abs(d).max() if d.size else 0.0
        g = g.copy()
        f_new, g_new = self._directional_evaluate(closure, x, t, d)
        ls_func_evals = 1
        gtd_new = float(g_new @ d)

        t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
        done = False
        ls_iter = 0
        while ls_iter < max_ls:
            if f_new > (f + c1 * t * gtd) or \
                    (ls_iter > 1 and f_new >= f_prev):
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new.copy()]
                bracket_gtd = [gtd_prev, gtd_new]
                break
            if abs(gtd_new) <= -c2 * gtd:
                bracket = [t, t]
                bracket_f = [f_new, f_new]
                bracket_g = [g_new, g_new]
                bracket_gtd = [gtd_new, gtd_new]
                done = True
                break
            if gtd_new >= 0:
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new.copy()]
                bracket_gtd = [gtd_prev, gtd_new]
                break

            min_step = t + 0.01 * (t - t_prev)
            max_step = t * 10
            tmp = t
            t = _cubic_interpolate(t_prev, f_prev, gtd_prev, t, f_new,
                                   gtd_new, bounds=(min_step, max_step))
            t_prev, f_prev, g_prev, gtd_prev = \
                tmp, f_new, g_new.copy(), gtd_new
            f_new, g_new = self._directional_evaluate(closure, x, t, d)
            ls_func_evals += 1
            gtd_new = float(g_new @ d)
            ls_iter += 1
        else:
            bracket = [0, t]
            bracket_f = [f, f_new]
            bracket_g = [g, g_new]
            bracket_gtd = [gtd, gtd_new]

        insuf_progress = False
        low_pos, high_pos = (0, 1) if bracket_f[0] <= bracket_f[-1] \
            else (1, 0)
        while not done and ls_iter < max_ls:
            if abs(bracket[1] - bracket[0]) * d_norm < tolerance_change:
                break
            t = _cubic_interpolate(bracket[0], bracket_f[0],
                                   bracket_gtd[0], bracket[1],
                                   bracket_f[1], bracket_gtd[1])
            eps = 0.1 * (max(bracket) - min(bracket))
            if min(max(bracket) - t, t - min(bracket)) < eps:
                if insuf_progress or t >= max(bracket) or \
                        t <= min(bracket):
                    if abs(t - max(bracket)) < abs(t - min(bracket)):
                        t = max(bracket) - eps
                    else:
                        t = min(bracket) + eps
                    insuf_progress = False
                else:
                    insuf_progress = True
            else:
                insuf_progress = False

            f_new, g_new = self._directional_evaluate(closure, x, t, d)
            ls_func_evals += 1
            gtd_new = float(g_new @ d)
            ls_iter += 1

            if f_new > (f + c1 * t * gtd) or f_new >= bracket_f[low_pos]:
                bracket[high_pos] = t
                bracket_f[high_pos] = f_new
                bracket_g[high_pos] = g_new.copy()
                bracket_gtd[high_pos] = gtd_new
                low_pos, high_pos = (0, 1) \
                    if bracket_f[0] <= bracket_f[1] else (1, 0)
            else:
                if abs(gtd_new) <= -c2 * gtd:
                    done = True
                elif gtd_new * (bracket[high_pos]
                                - bracket[low_pos]) >= 0:
                    bracket[high_pos] = bracket[low_pos]
                    bracket_f[high_pos] = bracket_f[low_pos]
                    bracket_g[high_pos] = bracket_g[low_pos]
                    bracket_gtd[high_pos] = bracket_gtd[low_pos]
                bracket[low_pos] = t
                bracket_f[low_pos] = f_new
                bracket_g[low_pos] = g_new.copy()
                bracket_gtd[low_pos] = gtd_new

        t = bracket[low_pos]
        f_new = bracket_f[low_pos]
        g_new = bracket_g[low_pos]
        return f_new, g_new, t, ls_func_evals

    # -- main step ---------------------------------------------------------
    def step(self, closure=None):
        if closure is None:
            raise RuntimeError("LBFGS.step requires a closure that "
                               "re-evaluates the model and returns "
                               "the loss")
        state = self._state
        lr = self.get_lr()

        orig_loss = closure()
        loss = float(orig_loss)
        state["func_evals"] += 1
        current_evals = 1

        params = self._parameter_list()
        flat_grad = _gather_flat(params, "grad")
        if float(np.abs(flat_grad).max() if flat_grad.size else 0.0) \
                <= self.tolerance_grad:
            return orig_loss

        n_iter = 0
        while n_iter < self.max_iter:
            n_iter += 1
            state["n_iter"] += 1

            if state["n_iter"] == 1:
                d = -flat_grad
                state["old_sks"], state["old_yks"], state["ro"] = \
                    [], [], []
                H_diag = 1.0
            else:
                y = flat_grad - state["prev_flat_grad"]
                s = state["d"] * state["t"]
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(state["old_sks"]) == self.history_size:
                        state["old_sks"].pop(0)
                        state["old_yks"].pop(0)
                        state["ro"].pop(0)
                    state["old_sks"].append(s)
                    state["old_yks"].append(y)
                    state["ro"].append(1.0 / ys)
                    H_diag = ys / float(y @ y)
                else:
                    H_diag = state["H_diag"]

                # two-loop recursion
                num_old = len(state["old_sks"])
                al = [0.0] * num_old
                q = -flat_grad
                for i in range(num_old - 1, -1, -1):
                    al[i] = float(state["old_sks"][i] @ q) \
                        * state["ro"][i]
                    q = q - al[i] * state["old_yks"][i]
                d = q * H_diag
                for i in range(num_old):
                    be_i = float(state["old_yks"][i] @ d) \
                        * state["ro"][i]
                    d = d + state["old_sks"][i] * (al[i] - be_i)

            state["H_diag"] = H_diag
            state["prev_flat_grad"] = flat_grad.copy()
            prev_loss = loss

            gtd = float(flat_grad @ d)
            if gtd > -self.tolerance_change:
                break

            if state["n_iter"] == 1:
                t = min(1.0, 1.0 / float(np.abs(flat_grad).sum())) * lr
            else:
                t = lr

            x0 = _gather_flat(params, "data")
            if self.line_search_fn == "strong_wolfe":
                loss, flat_grad, t, ls_evals = self._strong_wolfe(
                    closure, x0, t, d, loss, flat_grad, gtd)
                self._set_flat(x0 + t * d)
                current_evals += ls_evals
            else:
                self._set_flat(x0 + t * d)
                loss = float(closure())
                flat_grad = _gather_flat(params, "grad")
                current_evals += 1
                state["func_evals"] += 1

            state["d"], state["t"] = d, t

            if current_evals >= self.max_eval:
                break
            if float(np.abs(flat_grad).max()) <= self.tolerance_grad:
                break
            if float(np.abs(d * t).max()) <= self.tolerance_change:
                break
            if abs(loss - prev_loss) < self.tolerance_change:
                break

        return Tensor(jnp.asarray(loss, jnp.float32))

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list():
            p.clear_grad()

    def state_dict(self):
        s = self._state
        return {
            "func_evals": s["func_evals"], "n_iter": s["n_iter"],
            "old_sks": [np.asarray(v) for v in s["old_sks"]],
            "old_yks": [np.asarray(v) for v in s["old_yks"]],
            "ro": list(s["ro"]), "H_diag": s["H_diag"],
            "d": None if s["d"] is None else np.asarray(s["d"]),
            "t": s["t"],
            "prev_flat_grad": None if s["prev_flat_grad"] is None
            else np.asarray(s["prev_flat_grad"]),
        }

    def set_state_dict(self, state):
        s = self._state
        for k in ("func_evals", "n_iter", "ro", "H_diag", "t"):
            if k in state:
                s[k] = state[k]
        for k in ("old_sks", "old_yks"):
            if k in state:
                s[k] = [np.asarray(v, np.float64) for v in state[k]]
        for k in ("d", "prev_flat_grad"):
            if k in state and state[k] is not None:
                s[k] = np.asarray(state[k], np.float64)
