"""The declared-``__all__`` optimizer tail: Adamax, NAdam, RAdam,
Adadelta, Rprop, ASGD.

Reference semantics: ``python/paddle/optimizer/{adamax,nadam,radam,
adadelta,rprop,asgd}.py`` (update rules in each class docstring, math
matching the phi kernels ``phi/kernels/{adamax,nadam,radam,adadelta,
rprop,asgd}_kernel.h``).  Same style as optimizers.py: module-level
jitted update bodies so eager steps hit the XLA executable cache.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import Optimizer


@jax.jit
def _adamax_update(p, g, m, inf, lr, beta1, beta2, epsilon, b1pow):
    m = beta1 * m + (1 - beta1) * g
    inf = jnp.maximum(beta2 * inf + epsilon, jnp.abs(g))
    new_p = p - (lr / (1 - b1pow)) * m / inf
    return new_p, m, inf


class Adamax(Optimizer):
    """Adam variant on the infinity norm (reference
    ``python/paddle/optimizer/adamax.py:45``; update rule at :58-64)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, multi_precision, name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)

    def _update_param(self, p, pd, gd, lr, wd):
        m = self._get_accumulator(p, "moment", dtype=jnp.float32)
        inf = self._get_accumulator(p, "inf_norm", dtype=jnp.float32)
        t = self._step_count(p)
        new_p, m, inf = _adamax_update(
            pd.astype(jnp.float32), gd.astype(jnp.float32), m, inf, lr,
            self._beta1, self._beta2, self._epsilon, self._beta1 ** t)
        self._set_accumulator(p, "moment", m)
        self._set_accumulator(p, "inf_norm", inf)
        return new_p.astype(pd.dtype)


@jax.jit
def _nadam_update(p, g, m, v, mu_prod, lr, beta1, beta2, epsilon,
                  b2pow, mu_t, mu_t1):
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    mu_prod_t = mu_prod * mu_t
    mu_prod_t1 = mu_prod_t * mu_t1
    m_hat = mu_t1 * m / (1 - mu_prod_t1) + (1 - mu_t) * g / (1 - mu_prod_t)
    v_hat = v / (1 - b2pow)
    new_p = p - lr * m_hat / (jnp.sqrt(v_hat) + epsilon)
    return new_p, m, v


class NAdam(Optimizer):
    """Adam with Nesterov momentum (reference
    ``python/paddle/optimizer/nadam.py:49``; rule at :60-75 — the
    mu-product schedule with momentum_decay psi)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, multi_precision, name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._psi = float(momentum_decay)

    def _update_param(self, p, pd, gd, lr, wd):
        m = self._get_accumulator(p, "moment1", dtype=jnp.float32)
        v = self._get_accumulator(p, "moment2", dtype=jnp.float32)
        slots = self._accumulators.setdefault(id(p), {})
        mu_prod = slots.get("_mu_prod", 1.0)
        t = self._step_count(p)
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        new_p, m, v = _nadam_update(
            pd.astype(jnp.float32), gd.astype(jnp.float32), m, v,
            jnp.float32(mu_prod), lr, self._beta1, self._beta2,
            self._epsilon, self._beta2 ** t, mu_t, mu_t1)
        # mu_prod is a pure host-side scalar recurrence — keeping it out of
        # the jit outputs avoids one device fetch per parameter per step.
        slots["_mu_prod"] = mu_prod * mu_t
        self._set_accumulator(p, "moment1", m)
        self._set_accumulator(p, "moment2", v)
        return new_p.astype(pd.dtype)


@jax.jit
def _radam_update(p, g, m, v, lr, beta1, beta2, epsilon, b1pow, b2pow,
                  rho_t, rho_inf):
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    m_hat = m / (1 - b1pow)
    rectified = rho_t > 5.0
    l_t = jnp.sqrt(1 - b2pow) / (jnp.sqrt(v) + epsilon)
    r_t = jnp.sqrt((rho_t - 4) * (rho_t - 2) * rho_inf /
                   ((rho_inf - 4) * (rho_inf - 2) * rho_t))
    new_p = jnp.where(rectified, p - lr * m_hat * r_t * l_t,
                      p - lr * m_hat)
    return new_p, m, v


class RAdam(Optimizer):
    """Rectified Adam (reference ``python/paddle/optimizer/radam.py:49``;
    rule at :58-76 — variance-rectification term r_t gated on rho_t>5)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, multi_precision, name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)

    def _update_param(self, p, pd, gd, lr, wd):
        m = self._get_accumulator(p, "moment1", dtype=jnp.float32)
        v = self._get_accumulator(p, "moment2", dtype=jnp.float32)
        t = self._step_count(p)
        rho_inf = 2.0 / (1 - self._beta2) - 1
        b2pow = self._beta2 ** t
        rho_t = rho_inf - 2.0 * t * b2pow / (1 - b2pow)
        new_p, m, v = _radam_update(
            pd.astype(jnp.float32), gd.astype(jnp.float32), m, v, lr,
            self._beta1, self._beta2, self._epsilon, self._beta1 ** t,
            b2pow, jnp.float32(rho_t), jnp.float32(rho_inf))
        self._set_accumulator(p, "moment1", m)
        self._set_accumulator(p, "moment2", v)
        return new_p.astype(pd.dtype)


@jax.jit
def _adadelta_update(p, g, avg_sq_grad, avg_sq_update, lr, rho, epsilon):
    avg_sq_grad = rho * avg_sq_grad + (1 - rho) * g * g
    scale = jnp.sqrt((avg_sq_update + epsilon) / (avg_sq_grad + epsilon))
    delta = -scale * g
    avg_sq_update = rho * avg_sq_update + (1 - rho) * delta * delta
    return p + lr * delta, avg_sq_grad, avg_sq_update


class Adadelta(Optimizer):
    """Adadelta (reference ``python/paddle/optimizer/adadelta.py``;
    rule: E[g^2] / E[dx^2] running averages, scaled delta)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, multi_precision, name)
        self._rho = float(rho)
        self._epsilon = float(epsilon)

    def _update_param(self, p, pd, gd, lr, wd):
        asg = self._get_accumulator(p, "avg_squared_grad",
                                    dtype=jnp.float32)
        asu = self._get_accumulator(p, "avg_squared_update",
                                    dtype=jnp.float32)
        new_p, asg, asu = _adadelta_update(
            pd.astype(jnp.float32), gd.astype(jnp.float32), asg, asu, lr,
            self._rho, self._epsilon)
        self._set_accumulator(p, "avg_squared_grad", asg)
        self._set_accumulator(p, "avg_squared_update", asu)
        return new_p.astype(pd.dtype)


@jax.jit
def _rprop_update(p, g, prev_g, lrs, eta_neg, eta_pos, lr_min, lr_max):
    sign = jnp.sign(g * prev_g)
    lrs = jnp.clip(
        jnp.where(sign > 0, lrs * eta_pos,
                  jnp.where(sign < 0, lrs * eta_neg, lrs)),
        lr_min, lr_max)
    # on a sign flip the step is skipped and the stored grad zeroed so
    # the next step takes the "equal" branch
    g_eff = jnp.where(sign < 0, jnp.zeros_like(g), g)
    new_p = jnp.where(sign < 0, p, p - jnp.sign(g) * lrs)
    return new_p, g_eff, lrs


class Rprop(Optimizer):
    """Resilient backprop, full-batch rule (reference
    ``python/paddle/optimizer/rprop.py``; sign-agreement per-element
    learning rates in [learning_rate_range], etas multipliers)."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_min, self._lr_max = map(float, learning_rate_range)
        self._eta_neg, self._eta_pos = map(float, etas)
        self._init_lr = float(learning_rate) if isinstance(
            learning_rate, (int, float)) else 0.001

    def _update_param(self, p, pd, gd, lr, wd):
        prev = self._get_accumulator(p, "prev_grad", dtype=jnp.float32)
        slots = self._accumulators.setdefault(id(p), {})
        if "learning_rates" not in slots:
            slots["learning_rates"] = jnp.full(
                pd.shape, self._init_lr, jnp.float32)
        lrs = slots["learning_rates"]
        new_p, prev, lrs = _rprop_update(
            pd.astype(jnp.float32), gd.astype(jnp.float32), prev, lrs,
            self._eta_neg, self._eta_pos, self._lr_min, self._lr_max)
        self._set_accumulator(p, "prev_grad", prev)
        slots["learning_rates"] = lrs
        return new_p.astype(pd.dtype)


@jax.jit
def _asgd_update(p, g, d, y, lr, n_eff, wd):
    d = d - y + g
    new_p = p - lr * (d / n_eff + wd * p)
    return new_p, d, g


class ASGD(Optimizer):
    """SAG-style averaged stochastic gradient (reference
    ``python/paddle/optimizer/asgd.py``; rule at :52-60 — running sum d
    over the last ``batch_num`` per-index gradients y_i)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        if batch_num <= 0:
            raise ValueError("batch_num must be positive")
        self._n = int(batch_num)
        self._wd = float(weight_decay) if isinstance(
            weight_decay, (int, float)) else (
                weight_decay.coeff if weight_decay is not None else 0.0)

    def _update_param(self, p, pd, gd, lr, wd):
        d = self._get_accumulator(p, "d", dtype=jnp.float32)
        slots = self._accumulators.setdefault(id(p), {})
        m = slots.get("_m", 0)
        if "ys" not in slots:
            slots["ys"] = jnp.zeros((self._n,) + tuple(pd.shape),
                                    jnp.float32)
        i = m % self._n
        y_i = slots["ys"][i]
        n_eff = min(m + 1, self._n)
        new_p, d, y_new = _asgd_update(
            pd.astype(jnp.float32), gd.astype(jnp.float32), d, y_i, lr,
            jnp.float32(n_eff), jnp.float32(self._wd))
        slots["ys"] = slots["ys"].at[i].set(y_new)
        slots["_m"] = m + 1
        self._set_accumulator(p, "d", d)
        return new_p.astype(pd.dtype)
