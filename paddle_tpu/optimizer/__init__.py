from . import lr  # noqa: F401
from .optimizer import L1Decay, L2Decay, Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Adagrad, Adam, AdamW, Lamb, Lars, LarsMomentum, Momentum,
    RMSProp,
)
from .lbfgs import LBFGS  # noqa: F401
from .extra import (  # noqa: F401
    ASGD, Adadelta, Adamax, NAdam, RAdam, Rprop,
)
