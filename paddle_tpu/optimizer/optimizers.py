"""Concrete optimizers: SGD, Momentum, Adagrad, RMSProp, Adam, AdamW, Lamb.

Reference: ``python/paddle/optimizer/{sgd,momentum,adam,adamw,lamb}.py``
with update math matching the phi kernels (``phi/kernels/
{sgd,momentum,adam,adamw,lamb}_kernel...``).  Each update body is a
module-level jitted function so eager steps hit the XLA executable cache.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import L1Decay, L2Decay, Optimizer


@jax.jit
def _sgd_update(p, g, lr):
    return p - lr * g


@jax.jit
def _momentum_update(p, g, vel, lr, mu, use_nesterov):
    vel = mu * vel + g
    new_p = jnp.where(use_nesterov, p - (g + mu * vel) * lr, p - lr * vel)
    return new_p, vel


@jax.jit
def _adagrad_update(p, g, moment, lr, epsilon):
    moment = moment + g * g
    return p - lr * g / (jnp.sqrt(moment) + epsilon), moment


@jax.jit
def _rmsprop_update(p, g, mean_sq, mom, lr, rho, epsilon, momentum):
    mean_sq = rho * mean_sq + (1 - rho) * g * g
    mom = momentum * mom + lr * g / jnp.sqrt(mean_sq + epsilon)
    return p - mom, mean_sq, mom


@jax.jit
def _adam_update(p, g, m, v, lr, beta1, beta2, epsilon, b1pow, b2pow):
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    mhat = m / (1 - b1pow)
    vhat = v / (1 - b2pow)
    return p - lr * mhat / (jnp.sqrt(vhat) + epsilon), m, v


@jax.jit
def _adamw_update(p, g, m, v, lr, beta1, beta2, epsilon, b1pow, b2pow,
                  coeff):
    # Decoupled weight decay (reference: phi/kernels/adamw_kernel).
    p = p * (1.0 - lr * coeff)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    mhat = m / (1 - b1pow)
    vhat = v / (1 - b2pow)
    return p - lr * mhat / (jnp.sqrt(vhat) + epsilon), m, v


@jax.jit
def _lamb_update(p, g, m, v, lr, beta1, beta2, epsilon, b1pow, b2pow,
                 lamb_weight_decay):
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    mhat = m / (1 - b1pow)
    vhat = v / (1 - b2pow)
    r = mhat / (jnp.sqrt(vhat) + epsilon) + lamb_weight_decay * p
    w_norm = jnp.sqrt(jnp.sum(p * p))
    r_norm = jnp.sqrt(jnp.sum(r * r))
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return p - lr * ratio * r, m, v


class SGD(Optimizer):
    def _update_param(self, p, pd, gd, lr, wd):
        return _sgd_update(pd, gd, lr)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update_param(self, p, pd, gd, lr, wd):
        vel = self._get_accumulator(p, "velocity")
        if vel.dtype != pd.dtype:
            vel = vel.astype(pd.dtype)
        new_p, vel = _momentum_update(pd, gd, vel, lr, self._momentum,
                                      self._use_nesterov)
        self._set_accumulator(p, "velocity", vel)
        return new_p


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, pd, gd, lr, wd):
        mom = self._get_accumulator(
            p, "moment",
            init=jnp.full(tuple(p.shape), self._init_acc, pd.dtype))
        new_p, mom = _adagrad_update(pd, gd, mom, lr, self._epsilon)
        self._set_accumulator(p, "moment", mom)
        return new_p


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _update_param(self, p, pd, gd, lr, wd):
        ms = self._get_accumulator(p, "mean_square", dtype=pd.dtype)
        mom = self._get_accumulator(p, "momentum", dtype=pd.dtype)
        new_p, ms, mom = _rmsprop_update(pd, gd, ms, mom, lr, self._rho,
                                         self._epsilon, self._momentum)
        self._set_accumulator(p, "mean_square", ms)
        self._set_accumulator(p, "momentum", mom)
        return new_p


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update_param(self, p, pd, gd, lr, wd):
        m = self._get_accumulator(p, "moment1", dtype=jnp.float32)
        v = self._get_accumulator(p, "moment2", dtype=jnp.float32)
        t = self._step_count(p)
        gd32 = gd.astype(jnp.float32)
        pd32 = pd.astype(jnp.float32)
        new_p, m, v = _adam_update(pd32, gd32, m, v, lr, self._beta1,
                                   self._beta2, self._epsilon,
                                   self._beta1 ** t, self._beta2 ** t)
        self._set_accumulator(p, "moment1", m)
        self._set_accumulator(p, "moment2", v)
        return new_p.astype(pd.dtype)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         name=name)
        self._coeff = float(weight_decay) if isinstance(
            weight_decay, (int, float)) else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    @property
    def _apply_weight_decay_in_grad(self):
        return False

    def _update_param(self, p, pd, gd, lr, wd):
        coeff = self._coeff
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            coeff = 0.0
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        m = self._get_accumulator(p, "moment1", dtype=jnp.float32)
        v = self._get_accumulator(p, "moment2", dtype=jnp.float32)
        t = self._step_count(p)
        new_p, m, v = _adamw_update(pd.astype(jnp.float32),
                                    gd.astype(jnp.float32), m, v, lr,
                                    self._beta1, self._beta2, self._epsilon,
                                    self._beta1 ** t, self._beta2 ** t,
                                    coeff)
        self._set_accumulator(p, "moment1", m)
        self._set_accumulator(p, "moment2", v)
        return new_p.astype(pd.dtype)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, pd, gd, lr, wd):
        wd_coeff = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd_coeff = 0.0
        m = self._get_accumulator(p, "moment1", dtype=jnp.float32)
        v = self._get_accumulator(p, "moment2", dtype=jnp.float32)
        slots = self._accumulators.setdefault(id(p), {})
        t = slots.get("_t", 0) + 1
        slots["_t"] = t
        new_p, m, v = _lamb_update(pd.astype(jnp.float32),
                                   gd.astype(jnp.float32), m, v, lr,
                                   self._beta1, self._beta2, self._epsilon,
                                   self._beta1 ** t, self._beta2 ** t,
                                   wd_coeff)
        self._set_accumulator(p, "moment1", m)
        self._set_accumulator(p, "moment2", v)
        return new_p.astype(pd.dtype)


@jax.jit
def _lars_update(pd, gd, vel, lr, momentum, lars_coeff, lars_wd, eps):
    p32 = pd.astype(jnp.float32)
    g32 = gd.astype(jnp.float32)
    p_norm = jnp.sqrt(jnp.sum(p32 * p32))
    g_norm = jnp.sqrt(jnp.sum(g32 * g32))
    # layer-wise adaptive rate (LARS paper / reference lars_momentum op)
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lars_coeff * p_norm / (g_norm + lars_wd * p_norm + eps), 1.0)
    scaled = (g32 + lars_wd * p32) * local_lr * lr
    vel32 = momentum * vel.astype(jnp.float32) + scaled
    return (p32 - vel32).astype(pd.dtype), vel32


class LarsMomentum(Optimizer):
    """LARS (layer-wise adaptive rate scaling) momentum — reference
    ``lars_momentum`` kernel / paddle.incubate LarsMomentumOptimizer.
    Large-batch vision training (the reference's ResNet ImageNet
    recipes)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, epsilon=1e-9, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon

    def _update_param(self, p, pd, gd, lr, wd):
        vel = self._get_accumulator(p, "velocity", dtype=jnp.float32)
        new_p, vel = _lars_update(pd, gd, vel, lr, self._momentum,
                                  self._lars_coeff, self._lars_wd,
                                  self._epsilon)
        self._set_accumulator(p, "velocity", vel)
        return new_p


Lars = LarsMomentum
