"""Optimizer base.

Reference: ``python/paddle/optimizer/optimizer.py:125`` — parameter list /
param-group handling, accumulator state, LR (float or LRScheduler),
regularization, grad clip, ``step``/``clear_grad``/``state_dict``.

TPU-native: each optimizer provides a pure jitted ``_update`` over (param,
grad, *slots, lr) so the eager step is a cached XLA executable per shape;
the same ``_update`` is reused by ``paddle_tpu.jit`` to build fully
compiled train steps (the slots live in a pytree keyed like state_dict).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .lr import LRScheduler


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    _accumulator_names: tuple = ()

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._use_master_weights = multi_precision
        self._accumulators: dict[int, dict] = {}
        self._master_weights: dict[int, jnp.ndarray] = {}
        self._global_step = 0

        if weight_decay is None:
            self._weight_decay = None
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = L2Decay(float(weight_decay))
        else:
            self._weight_decay = weight_decay

        self._param_groups = []
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                for group in parameters:
                    g = dict(group)
                    g.setdefault("learning_rate", 1.0)
                    g["params"] = list(g["params"])
                    self._param_groups.append(g)
            else:
                self._param_groups.append({"params": parameters,
                                           "learning_rate": 1.0})
        self._parameters_provided = parameters is not None

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is a scheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- parameters ----------------------------------------------------------
    def _parameter_list(self):
        out = []
        for g in self._param_groups:
            out.extend(g["params"])
        return out

    @property
    def _parameter_groups(self):
        return self._param_groups

    # -- state -------------------------------------------------------------
    def _get_accumulator(self, p, name, init=None, dtype=None):
        slots = self._accumulators.setdefault(id(p), {})
        if name not in slots:
            d = dtype or (jnp.float32 if self._use_master_weights
                          else p.dtype)
            slots[name] = jnp.zeros(tuple(p.shape), d) if init is None \
                else init
        return slots[name]

    def _set_accumulator(self, p, name, value):
        self._accumulators.setdefault(id(p), {})[name] = value

    def _step_count(self, p):
        """Per-parameter step counter (host-side scalar slot)."""
        slots = self._accumulators.setdefault(id(p), {})
        t = slots.get("_t", 0) + 1
        slots["_t"] = t
        return t

    def _master_weight(self, p):
        mw = self._master_weights.get(id(p))
        if mw is None:
            mw = p._data.astype(jnp.float32)
            self._master_weights[id(p)] = mw
        return mw

    # -- step --------------------------------------------------------------
    def step(self):
        self._global_step += 1
        for group in self._param_groups:
            params_grads = [(p, p.grad) for p in group["params"]
                            if p.grad is not None and p.trainable]
            if not params_grads:
                continue
            if self._grad_clip is not None:
                params_grads = self._grad_clip(params_grads)
            group_lr = self.get_lr() * group.get("learning_rate", 1.0)
            wd = group.get("weight_decay", self._weight_decay)
            if isinstance(wd, (int, float)):
                wd = L2Decay(float(wd))
            for p, g in params_grads:
                lr = group_lr * p.optimize_attr.get("learning_rate", 1.0) \
                    if hasattr(p, "optimize_attr") else group_lr
                self._apply_one(p, g, lr, wd)

    @property
    def _apply_weight_decay_in_grad(self):
        return True

    def _apply_one(self, p, g, lr, wd):
        gd = g._data
        use_master = (self._use_master_weights
                      and p.dtype != jnp.float32)
        pd = self._master_weight(p) if use_master else p._data
        if gd.dtype != pd.dtype:
            gd = gd.astype(pd.dtype)
        if wd is not None and self._apply_weight_decay_in_grad \
                and getattr(p, "regularizer", None) is None:
            if isinstance(wd, L2Decay) and wd.coeff:
                gd = gd + wd.coeff * pd
            elif isinstance(wd, L1Decay) and wd.coeff:
                gd = gd + wd.coeff * jnp.sign(pd)
        new_p = self._update_param(p, pd, gd, lr, wd)
        if use_master:
            self._master_weights[id(p)] = new_p
            p._data = new_p.astype(p.dtype)
        else:
            p._data = new_p

    def _update_param(self, p, pd, gd, lr, wd):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list():
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # -- serialization -----------------------------------------------------
    def state_dict(self):
        state = {"global_step": self._global_step}
        accum = {}
        for i, p in enumerate(self._parameter_list()):
            slots = self._accumulators.get(id(p), {})
            key = p.name or f"param_{i}"
            for sname, val in slots.items():
                accum[f"{key}.{sname}"] = np.asarray(val)
            if id(p) in self._master_weights:
                accum[f"{key}.master_weight"] = np.asarray(
                    self._master_weights[id(p)])
        state["accumulators"] = accum
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        return state

    def set_state_dict(self, state):
        self._global_step = state.get("global_step", 0)
        accum = state.get("accumulators", {})
        for i, p in enumerate(self._parameter_list()):
            key = p.name or f"param_{i}"
            for full, val in accum.items():
                if not full.startswith(key + "."):
                    continue
                sname = full[len(key) + 1:]
                if sname == "master_weight":
                    self._master_weights[id(p)] = jnp.asarray(val)
                else:
                    self._set_accumulator(p, sname, jnp.asarray(val))
        if "LR_Scheduler" in state and isinstance(self._learning_rate,
                                                  LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])

    load_state_dict = set_state_dict
