"""paddle.sparse — COO/CSR sparse tensors and ops.

Reference: ``python/paddle/sparse/`` — ``sparse_coo_tensor`` /
``sparse_csr_tensor`` (creation.py:83,204), binary ops
(binary.py: matmul:62, masked_matmul:140, mv:206, add/subtract/
multiply/divide, mask_as:511, is_same_shape:478), value-wise unary ops
(unary.py), and ``Tensor.to_dense``/``to_sparse_coo``/``to_sparse_csr``.

TPU-native design: XLA has no sparse kernels — sparse compute lowers to
dense gather/scatter/segment ops, which is also how the reference's GPU
kernels behave for these shapes (cuSPARSE aside).  A ``SparseTensor``
holds immutable integer layout arrays (COO ``indices`` [ndim, nnz] or
CSR ``crows``/``cols``) plus a VALUES tensor that is a first-class
``paddle_tpu`` Tensor: every op here dispatches through the op registry
on the values (layout arrays ride along as non-differentiable inputs),
so gradients flow to ``values`` — and through ``matmul``'s dense operand
— exactly like the reference's differentiable sparse ops.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry

_op = _registry.cached_apply


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class SparseTensor:
    """COO or CSR sparse tensor (values differentiable)."""

    def __init__(self, fmt, shape, values, indices=None, crows=None,
                 cols=None):
        assert fmt in ("coo", "csr")
        self._fmt = fmt
        self._shape = tuple(int(s) for s in shape)
        self.values_t = values if isinstance(values, Tensor) \
            else Tensor(jnp.asarray(values))
        self._indices = indices  # [ndim, nnz] int (coo)
        self._crows = crows      # [nrows+1] int (csr)
        self._cols = cols        # [nnz] int (csr)

    # -- introspection -------------------------------------------------------
    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values_t.dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def nnz(self):
        return int(self.values_t._data.shape[0])

    @property
    def stop_gradient(self):
        return self.values_t.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.values_t.stop_gradient = v

    def is_sparse_coo(self):
        return self._fmt == "coo"

    def is_sparse_csr(self):
        return self._fmt == "csr"

    def indices(self):
        if self._fmt != "coo":
            raise ValueError("indices() requires a COO tensor")
        return Tensor(self._indices, stop_gradient=True)

    def values(self):
        return self.values_t

    def crows(self):
        if self._fmt != "csr":
            raise ValueError("crows() requires a CSR tensor")
        return Tensor(self._crows, stop_gradient=True)

    def cols(self):
        if self._fmt != "csr":
            raise ValueError("cols() requires a CSR tensor")
        return Tensor(self._cols, stop_gradient=True)

    # -- conversions ---------------------------------------------------------
    def _coo_indices(self):
        """[ndim, nnz] index rows regardless of format (2-D for csr)."""
        if self._fmt == "coo":
            return self._indices
        counts = jnp.diff(self._crows)
        rows = jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self._cols.shape[0])
        return jnp.stack([rows.astype(self._cols.dtype), self._cols])

    def to_dense(self):
        idx = self._coo_indices()

        def fn(values, idx, shape):
            out = jnp.zeros(shape, values.dtype)
            return out.at[tuple(idx[i] for i in range(idx.shape[0]))
                          ].add(values)

        return _op("sparse_to_dense", fn, self.values_t, idx,
                   shape=self._shape)

    def to_sparse_coo(self, sparse_dim=None):
        if self._fmt == "coo":
            return self
        return SparseTensor("coo", self._shape, self.values_t,
                            indices=self._coo_indices())

    def to_sparse_csr(self):
        if self._fmt == "csr":
            return self
        if self.ndim != 2:
            raise ValueError("CSR requires 2-D")
        rows, cols = self._indices[0], self._indices[1]
        order = jnp.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        vals = Tensor(self.values_t._data[order],
                      stop_gradient=self.values_t.stop_gradient)
        crows = jnp.concatenate([
            jnp.zeros((1,), rows.dtype),
            jnp.cumsum(jnp.bincount(rows, length=self._shape[0]))
        ]).astype(rows.dtype)
        return SparseTensor("csr", self._shape, vals, crows=crows,
                            cols=cols)

    def coalesce(self):
        """Merge duplicate COO coordinates (values summed)."""
        if self._fmt != "coo":
            return self
        idx = np.asarray(self._indices)
        flat = np.ravel_multi_index(idx, self._shape)
        uniq, inv = np.unique(flat, return_inverse=True)
        new_idx = jnp.asarray(np.stack(
            np.unravel_index(uniq, self._shape)))

        def fn(values, inv, n):
            seg = jax.ops.segment_sum(values, inv, num_segments=n)
            return seg

        vals = _op("sparse_coalesce", fn, self.values_t,
                   jnp.asarray(inv), n=int(uniq.shape[0]))
        return SparseTensor("coo", self._shape, vals, indices=new_idx)

    def __repr__(self):
        return (f"SparseTensor(fmt={self._fmt}, shape={self._shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


# -- creation (reference creation.py:83,204) --------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = jnp.asarray(_raw(indices)).astype(jnp.int32)
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1)))
    if isinstance(values, Tensor) and dtype is None:
        v = values  # keep tape identity — grads flow to the caller's
    else:
        from ..core import dtype as _dt

        vals = _raw(values)
        if dtype is not None:
            vals = vals.astype(_dt.convert_dtype(dtype))
        v = Tensor(vals, stop_gradient=stop_gradient)
    return SparseTensor("coo", shape, v, indices=idx)


def dense_to_coo(t, sparse_dim=None):
    """Tensor -> COO SparseTensor (Tensor.to_sparse_coo backend).

    The index pattern comes from a host-side ``nonzero`` (inherently
    data-dependent), but the VALUES are gathered through the op registry
    so gradients flow back to the dense source (reference
    to_sparse_coo is differentiable)."""
    nd = t._data.ndim
    sd = nd if sparse_dim is None else int(sparse_dim)
    dense_np = np.asarray(jax.lax.stop_gradient(t._data))
    if sd == nd:
        idx = jnp.asarray(np.stack(np.nonzero(dense_np)), jnp.int32)
    else:
        # hybrid COO (reference to_sparse_coo(sparse_dim)): the first
        # sd dims are sparse, trailing dims stay dense in the values —
        # a site is active when ANY trailing element is nonzero.
        red = tuple(range(sd, nd))
        active = dense_np.reshape(dense_np.shape[:sd] + (-1,)).any(-1)
        idx = jnp.asarray(np.stack(np.nonzero(active)), jnp.int32)

    def fn(dense, idx):
        return dense[tuple(idx[i] for i in range(idx.shape[0]))]

    vals = _op("sparse_gather_values", fn, t, idx)
    return SparseTensor("coo", dense_np.shape, vals, indices=idx)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    crows = jnp.asarray(_raw(crows)).astype(jnp.int32)
    cols = jnp.asarray(_raw(cols)).astype(jnp.int32)
    v = values if isinstance(values, Tensor) else Tensor(_raw(values))
    if dtype is not None:
        from ..core import dtype as _dt

        v = Tensor(v._data.astype(_dt.convert_dtype(dtype)))
    return SparseTensor("csr", shape, v, crows=crows, cols=cols)


# -- binary ops (reference binary.py) ---------------------------------------

def _same_pattern(x, y):
    if x._fmt != y._fmt or x._shape != y._shape:
        return False
    if x._fmt == "coo":
        return x._indices.shape == y._indices.shape and bool(
            jnp.all(x._indices == y._indices))
    return x._crows.shape == y._crows.shape and bool(
        jnp.all(x._crows == y._crows)) and bool(
        jnp.all(x._cols == y._cols))


def _ewise(name, fn, x, y):
    if not _same_pattern(x, y):
        raise ValueError(
            f"sparse.{name}: operands must share the sparsity pattern "
            "(reference kernels require same indices); call .coalesce() "
            "or convert formats first")
    vals = _op(f"sparse_{name}", fn, x.values_t, y.values_t)
    if x._fmt == "coo":
        return SparseTensor("coo", x._shape, vals, indices=x._indices)
    return SparseTensor("csr", x._shape, vals, crows=x._crows,
                        cols=x._cols)


def add(x, y, name=None):
    return _ewise("add", lambda a, b: a + b, x, y)


def subtract(x, y, name=None):
    return _ewise("subtract", lambda a, b: a - b, x, y)


def multiply(x, y, name=None):
    return _ewise("multiply", lambda a, b: a * b, x, y)


def divide(x, y, name=None):
    return _ewise("divide", lambda a, b: a / b, x, y)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def matmul(x, y, name=None):
    """sparse [M, N] @ dense [N, K] -> dense [M, K] (binary.py:62);
    differentiable w.r.t. both the sparse values and the dense operand."""
    if isinstance(x, SparseTensor):
        idx = x._coo_indices()
        M = x._shape[0]

        def fn(values, dense, idx, M):
            rows, cols = idx[0], idx[1]
            contrib = values[:, None] * dense[cols]
            return jax.ops.segment_sum(contrib, rows, num_segments=M)

        return _op("sparse_matmul", fn, x.values_t, y, idx, M=M)
    raise TypeError("sparse.matmul expects a SparseTensor lhs")


def mv(x, vec, name=None):
    """sparse [M, N] @ dense [N] -> dense [M] (binary.py:206)."""
    idx = x._coo_indices()
    M = x._shape[0]

    def fn(values, v, idx, M):
        rows, cols = idx[0], idx[1]
        return jax.ops.segment_sum(values * v[cols], rows,
                                   num_segments=M)

    return _op("sparse_mv", fn, x.values_t, vec, idx, M=M)


def masked_matmul(x, y, mask, name=None):
    """dense [M, N] @ dense [N, K] sampled at ``mask``'s sparsity
    pattern -> sparse (binary.py:140, the SDDMM kernel)."""
    idx = mask._coo_indices()

    def fn(a, b, idx):
        rows, cols = idx[0], idx[1]
        return jnp.einsum("nk,nk->n", a[rows], b.T[cols])

    vals = _op("sparse_masked_matmul", fn, x, y, idx)
    if mask._fmt == "coo":
        return SparseTensor("coo", mask._shape, vals,
                            indices=mask._indices)
    return SparseTensor("csr", mask._shape, vals, crows=mask._crows,
                        cols=mask._cols)


def mask_as(x, mask, name=None):
    """Sample dense ``x`` at ``mask``'s pattern -> sparse (binary.py:511)."""
    idx = mask._coo_indices()

    def fn(dense, idx):
        return dense[tuple(idx[i] for i in range(idx.shape[0]))]

    vals = _op("sparse_mask_as", fn, x, idx)
    if mask._fmt == "coo":
        return SparseTensor("coo", mask._shape, vals,
                            indices=mask._indices)
    return SparseTensor("csr", mask._shape, vals, crows=mask._crows,
                        cols=mask._cols)


# -- unary value ops (reference unary.py; zero-preserving only) -------------

def _unary(name, jfn):
    def op(x, name_=None):
        vals = _op(f"sparse_{name}", jfn, x.values_t)
        if x._fmt == "coo":
            return SparseTensor("coo", x._shape, vals,
                                indices=x._indices)
        return SparseTensor("csr", x._shape, vals, crows=x._crows,
                            cols=x._cols)

    op.__name__ = name
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
abs = _unary("abs", jnp.abs)  # noqa: A001
neg = _unary("neg", jnp.negative)
relu = _unary("relu", lambda v: jnp.maximum(v, 0))
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)


def pow(x, factor, name=None):  # noqa: A001
    vals = _op("sparse_pow", lambda v, factor: v ** factor, x.values_t,
               factor=float(factor))
    if x._fmt == "coo":
        return SparseTensor("coo", x._shape, vals, indices=x._indices)
    return SparseTensor("csr", x._shape, vals, crows=x._crows,
                        cols=x._cols)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core import dtype as _dt

    vals = x.values_t
    if value_dtype is not None:
        vals = _op("sparse_cast",
                   lambda v, dt: v.astype(dt), vals,
                   dt=_dt.convert_dtype(value_dtype))
    out = SparseTensor(x._fmt, x._shape, vals, indices=x._indices,
                       crows=x._crows, cols=x._cols)
    if index_dtype is not None:
        idt = _dt.convert_dtype(index_dtype)
        if out._indices is not None:
            out._indices = out._indices.astype(idt)
        if out._crows is not None:
            out._crows = out._crows.astype(idt)
            out._cols = out._cols.astype(idt)
    return out


def transpose(x, perm, name=None):
    if x._fmt != "coo":
        return transpose(x.to_sparse_coo(), perm, name)
    idx = x._indices[jnp.asarray(perm)]
    shape = tuple(x._shape[p] for p in perm)
    return SparseTensor("coo", shape, x.values_t, indices=idx)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Reference unary.py:188 — returns a dense Tensor reduction."""
    dense_sum = _op("sparse_sum_values",
                    lambda v: jnp.sum(v), x.values_t)
    if axis is None:
        return dense_sum
    return __import__("paddle_tpu").sum(x.to_dense(), axis=axis,
                                        keepdim=keepdim)


# -- nn sub-namespace -------------------------------------------------------

class _SparseReLU:
    def __call__(self, x):
        return relu(x)


class nn:  # noqa: N801 — namespace shim (reference paddle.sparse.nn)
    ReLU = _SparseReLU


# -- round-4 tail: missing __all__ entries + the nn layer family -------------

def coalesce(x, name=None):
    """reference sparse/unary.coalesce: merge duplicate coo indices
    (values summed), sort by index."""
    assert x.is_sparse_coo()
    import numpy as np

    idx = np.asarray(_raw(x._indices))
    vals = x.values_t
    flat = np.ravel_multi_index(idx, x._shape[:idx.shape[0]])
    order = np.argsort(flat, kind="stable")
    sorted_flat = flat[order]
    uniq, first = np.unique(sorted_flat, return_index=True)
    from .. import ops

    v_sorted = ops.gather(vals, Tensor(jnp.asarray(order)))
    # segment-sum duplicates
    seg = np.searchsorted(uniq, sorted_flat)

    def fn(v, seg, n):
        import jax

        return jax.ops.segment_sum(v, seg, num_segments=n)

    from ..ops import registry as _registry

    new_vals = _registry.cached_apply(
        "sparse_coalesce_sum", fn, v_sorted,
        Tensor(jnp.asarray(seg)), n=len(uniq))
    new_idx = jnp.asarray(np.stack(np.unravel_index(
        uniq, x._shape[:idx.shape[0]])))
    return SparseTensor("coo", x._shape, new_vals, indices=new_idx)


def reshape(x, shape, name=None):
    """reference sparse/unary.reshape (coo): recompute indices."""
    assert x.is_sparse_coo()
    import numpy as np

    new_shape = []
    n_elem = int(np.prod(x._shape))
    known = int(np.prod([s for s in shape if s != -1]))
    new_shape = [n_elem // known if s == -1 else int(s) for s in shape]
    idx = np.asarray(_raw(x._indices))
    flat = np.ravel_multi_index(idx, x._shape)
    new_idx = np.stack(np.unravel_index(flat, new_shape))
    return SparseTensor("coo", new_shape, x.values_t,
                        indices=jnp.asarray(new_idx))


def isnan(x, name=None):
    return _unary_apply("sparse_isnan", jnp.isnan, x)


def _unary_apply(name, jfn, x):
    from ..ops import registry as _registry

    new_vals = _registry.cached_apply(name, lambda v: jfn(v),
                                      x.values_t)
    if x.is_sparse_coo():
        return SparseTensor("coo", x._shape, new_vals,
                            indices=x._indices)
    return SparseTensor("csr", x._shape, new_vals, crows=x._crows,
                        cols=x._cols)


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    """reference sparse/unary.slice (coo): filter + shift indices."""
    assert x.is_sparse_coo()
    import numpy as np

    idx = np.asarray(_raw(x._indices))
    shape = list(x._shape)
    keep = np.ones(idx.shape[1], bool)
    out_shape = list(shape)
    for ax, s, e in zip(axes, starts, ends):
        s = s + shape[ax] if s < 0 else s
        e = e + shape[ax] if e < 0 else min(e, shape[ax])
        keep &= (idx[ax] >= s) & (idx[ax] < e)
        out_shape[ax] = e - s
    sel = np.nonzero(keep)[0]
    new_idx = idx[:, sel].copy()
    for ax, s, e in zip(axes, starts, ends):
        s = s + shape[ax] if s < 0 else s
        new_idx[ax] -= s
    from .. import ops

    new_vals = ops.gather(x.values_t, Tensor(jnp.asarray(sel)))
    return SparseTensor("coo", out_shape, new_vals,
                        indices=jnp.asarray(new_idx))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """reference sparse/multiary.addmm: beta*input + alpha*(x @ y)."""
    out = matmul(x, y)
    from .. import ops

    return ops.add(ops.scale(input, float(beta)),
                   ops.scale(out, float(alpha)))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference sparse/unary.pca_lowrank — randomized PCA over the
    densified matrix (TPU has no sparse SVD; n is small where this is
    used)."""
    d = x.to_dense() if isinstance(x, SparseTensor) else x
    from .. import ops

    m, n = d.shape[-2], d.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        d = d - ops.mean(d, axis=-2, keepdim=True)
    u, s, v = ops.svd(d, full_matrices=False)
    from ..ops import registry as _registry

    def cut(t, k):
        return _registry.cached_apply(
            "pca_cut", lambda a, k: a[..., :k], t, k=int(k))

    def cutv(t, k):
        return _registry.cached_apply(
            "pca_cutv", lambda a, k: a[..., :k], t, k=int(k))

    return cut(u, q), cut(s, q), cutv(ops.transpose(v, [1, 0])
                                      if v.ndim == 2 else v, q)


# -- sparse nn layer family (reference sparse/nn/layer) ----------------------

class _SparseActivation:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x):
        return self._fn(x)


def relu6(x, name=None):
    return _unary_apply("sparse_relu6", lambda v: jnp.clip(v, 0, 6), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from ..ops import registry as _registry

    new_vals = _registry.cached_apply(
        "sparse_leaky_relu",
        lambda v, s: jnp.where(v >= 0, v, s * v), x.values_t,
        s=float(negative_slope))
    if x.is_sparse_coo():
        return SparseTensor("coo", x._shape, new_vals,
                            indices=x._indices)
    return SparseTensor("csr", x._shape, new_vals, crows=x._crows,
                        cols=x._cols)


def softmax_sparse(x, axis=-1, name=None):
    """Softmax over the last axis of a 2-D CSR matrix computed on the
    stored values only (reference sparse softmax semantics)."""
    assert x.is_sparse_csr() and axis in (-1, x.ndim - 1)
    import numpy as np

    crows = np.asarray(_raw(x._crows))
    nnz = x.nnz
    row_of = np.repeat(np.arange(len(crows) - 1),
                       np.diff(crows)).astype(np.int32)

    def fn(v, rows, n_rows):
        import jax

        mx = jax.ops.segment_max(v, rows, num_segments=n_rows)
        e = jnp.exp(v - mx[rows])
        s = jax.ops.segment_sum(e, rows, num_segments=n_rows)
        return e / s[rows]

    from ..ops import registry as _registry

    new_vals = _registry.cached_apply(
        "sparse_softmax", fn, x.values_t,
        Tensor(jnp.asarray(row_of)), n_rows=len(crows) - 1)
    return SparseTensor("csr", x._shape, new_vals, crows=x._crows,
                        cols=x._cols)


class _SparseBatchNorm:
    """BatchNorm over the nnz values per channel (reference
    sparse/nn/layer/norm.py BatchNorm: input is [N, ..., C] coo;
    stats over stored values)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 data_format="NDHWC", name=None):
        from .. import nn as dense_nn

        self._bn = dense_nn.BatchNorm1D(num_features)

    def train(self):
        self._bn.train()

    def eval(self):
        self._bn.eval()

    def __call__(self, x):
        assert x.is_sparse_coo()
        vals = x.values_t  # [nnz, C]
        out = self._bn(vals)
        return SparseTensor("coo", x._shape, out, indices=x._indices)


def _dense_window_conv(fmt):
    class _SparseConv(  # noqa: N801
            object):
        """Sparse conv computed by densify -> dense conv -> re-sparsify
        on the output pattern (submanifold keeps the INPUT pattern —
        reference sparse/nn/layer/conv.py SubmConv3D semantics).  The
        TPU story for true gather-scatter sparse conv is the dense MXU
        (block-sparse patterns don't beat dense until extreme sparsity);
        semantics match the reference for the supported NDHWC layout."""

        subm = fmt.startswith("subm")
        nd = 3 if fmt.endswith("3d") else 2

        def __init__(self, in_channels, out_channels, kernel_size,
                     stride=1, padding=0, dilation=1, groups=1,
                     padding_mode="zeros", weight_attr=None,
                     bias_attr=None, data_format=None):
            from .. import nn as dense_nn

            cls = dense_nn.Conv3D if self.nd == 3 else dense_nn.Conv2D
            self._conv = cls(in_channels, out_channels, kernel_size,
                             stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             weight_attr=weight_attr,
                             bias_attr=bias_attr)

        def parameters(self):
            return self._conv.parameters()

        def __call__(self, x):
            assert x.is_sparse_coo()
            import numpy as np

            dense = x.to_dense()  # [N, *spatial, C]
            perm = [0, self.nd + 1] + list(range(1, self.nd + 1))
            from .. import ops

            d = ops.transpose(dense, perm)  # channel-first
            out = self._conv(d)
            inv = [0] + list(range(2, self.nd + 2)) + [1]
            out = ops.transpose(out, inv)
            if self.subm:
                # submanifold: output keeps the input's active sites
                # (hybrid indices cover the sparse dims only; trailing
                # channel dim rides along in the values)
                idx = np.asarray(_raw(x._indices))
                data = out._data[tuple(jnp.asarray(idx[i])
                                       for i in range(idx.shape[0]))]
                return SparseTensor(
                    "coo", list(out.shape), Tensor(data),
                    indices=x._indices)
            return dense_to_coo(out, sparse_dim=out.ndim - 1)

    return _SparseConv


nn.ReLU6 = _SparseActivation(relu6)
nn.LeakyReLU = lambda negative_slope=0.01: _SparseActivation(  # noqa: E731
    lambda x: leaky_relu(x, negative_slope))
nn.Softmax = _SparseActivation(softmax_sparse)
nn.BatchNorm = _SparseBatchNorm
nn.SyncBatchNorm = _SparseBatchNorm
nn.Conv2D = _dense_window_conv("conv2d")
nn.Conv3D = _dense_window_conv("conv3d")
nn.SubmConv2D = _dense_window_conv("subm2d")
nn.SubmConv3D = _dense_window_conv("subm3d")


class _SparseMaxPool3D:
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def __call__(self, x):
        assert x.is_sparse_coo()
        from .. import nn as dense_nn
        from .. import ops
        from ..nn import functional as dF

        dense = x.to_dense()  # [N, D, H, W, C]
        d = ops.transpose(dense, [0, 4, 1, 2, 3])
        out = dF.max_pool3d(d, self.kernel_size, self.stride,
                            self.padding)
        out = ops.transpose(out, [0, 2, 3, 4, 1])
        return dense_to_coo(out, sparse_dim=out.ndim - 1)


nn.MaxPool3D = _SparseMaxPool3D
