"""paddle.signal — frame / overlap_add / stft / istft.

Reference: ``python/paddle/signal.py`` (frame:38, overlap_add:161,
stft:266, istft:443).

TPU-native: framing is one gather, the transform is the XLA FFT HLO,
and istft's overlap-add is a segment-sum — each API is a single jitted
program through the op registry.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor
from .ops import registry as _registry

_op = _registry.cached_apply

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice ``x`` into overlapping frames along ``axis`` (signal.py:38).
    axis=-1: [..., T] -> [..., frame_length, num_frames];
    axis=0:  [T, ...] -> [num_frames, frame_length, ...]."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")

    def fn(a, frame_length, hop_length, axis):
        T = a.shape[axis]
        if T < frame_length:
            raise ValueError(
                f"input too short: {T} < frame_length {frame_length}")
        n = 1 + (T - frame_length) // hop_length
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        if axis == -1:
            seg = a[..., idx]              # [..., n, frame_length]
            return jnp.swapaxes(seg, -1, -2)
        seg = a[idx]                       # [n, frame_length, ...]
        return seg

    return _op("signal_frame", fn, _t(x), frame_length=int(frame_length),
               hop_length=int(hop_length), axis=int(axis))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: sum overlapping frames (signal.py:161).
    axis=-1: [..., frame_length, n] -> [..., T];
    axis=0:  [n, frame_length, ...] -> [T, ...]."""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")

    def fn(a, hop_length, axis):
        if axis == -1:
            fl, n = a.shape[-2], a.shape[-1]
            frames = jnp.moveaxis(a, -1, -2)  # [..., n, fl]
        else:
            n, fl = a.shape[0], a.shape[1]
            frames = jnp.moveaxis(a, (0, 1), (-2, -1))  # [..., n, fl]
        T = (n - 1) * hop_length + fl
        starts = jnp.arange(n) * hop_length
        idx = (starts[:, None] + jnp.arange(fl)[None, :]).reshape(-1)
        flat = frames.reshape(frames.shape[:-2] + (n * fl,))
        out = jax.vmap(
            lambda row: jax.ops.segment_sum(row, idx, num_segments=T),
        )(flat.reshape(-1, n * fl)).reshape(frames.shape[:-2] + (T,))
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out

    return _op("signal_overlap_add", fn, _t(x),
               hop_length=int(hop_length), axis=int(axis))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """Short-time Fourier transform (signal.py:266).

    x: [B, T] (or [T]) real or complex; returns [B, n_fft//2+1 or
    n_fft, num_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    x_data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    w_probe = window._data if isinstance(window, Tensor) else window
    if onesided and (jnp.iscomplexobj(x_data) or
                     (w_probe is not None and
                      jnp.iscomplexobj(w_probe))):
        # Reference stft asserts onesided must be False for complex
        # inputs; silently returning n_fft bins broke callers (ADVICE r3).
        raise ValueError(
            "stft: onesided is not supported for complex input or "
            "complex window; pass onesided=False")
    if window is not None:
        w = window._data if isinstance(window, Tensor) else \
            jnp.asarray(window)
    else:
        w = jnp.ones(win_length, jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))

    def fn(a, w, n_fft, hop_length, center, pad_mode, normalized,
           onesided):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        T = a.shape[-1]
        if T < n_fft:
            raise ValueError(f"signal too short: {T} < n_fft {n_fft}")
        n = 1 + (T - n_fft) // hop_length
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        seg = a[..., idx] * w[None, None, :]
        if jnp.iscomplexobj(seg) or not onesided:
            spec = jnp.fft.fft(seg, axis=-1)
        else:
            spec = jnp.fft.rfft(seg, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -1, -2)   # [B, bins, frames]
        return out[0] if squeeze else out

    return _op("signal_stft", fn, _t(x), Tensor(w), n_fft=int(n_fft),
               hop_length=int(hop_length), center=bool(center),
               pad_mode=str(pad_mode), normalized=bool(normalized),
               onesided=bool(onesided))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization (signal.py:443)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        w = window._data if isinstance(window, Tensor) else \
            jnp.asarray(window)
    else:
        w = jnp.ones(win_length, jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))

    def fn(spec, w, n_fft, hop_length, center, normalized, onesided,
           length, return_complex):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        spec = jnp.swapaxes(spec, -1, -2)  # [B, frames, bins]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        if onesided:
            seg = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            seg = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                seg = seg.real
        seg = seg * w[None, None, :]
        B, n = seg.shape[0], seg.shape[1]
        T = (n - 1) * hop_length + n_fft
        starts = jnp.arange(n) * hop_length
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        num = jax.vmap(lambda row: jax.ops.segment_sum(
            row, idx, num_segments=T))(seg.reshape(B, -1))
        env = jax.ops.segment_sum(
            jnp.tile(w * w, n), idx, num_segments=T)
        out = num / jnp.maximum(env, 1e-11)[None]
        if center:
            pad = n_fft // 2
            out = out[..., pad:T - pad]
        if length is not None:
            out = out[..., :length]
        return out[0] if squeeze else out

    return _op("signal_istft", fn, _t(x), Tensor(w), n_fft=int(n_fft),
               hop_length=int(hop_length), center=bool(center),
               normalized=bool(normalized), onesided=bool(onesided),
               length=None if length is None else int(length),
               return_complex=bool(return_complex))
