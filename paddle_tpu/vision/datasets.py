"""Vision datasets.

Reference: ``python/paddle/vision/datasets/`` (MNIST mnist.py, Cifar
cifar.py, FashionMNIST).  Same file formats and __getitem__ contracts;
`download=True` is unsupported in this environment (no egress) — point
``image_path``/``data_file`` at local copies, or use FakeImageDataset for
pipeline work without data on disk.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ..io import Dataset


class FakeImageDataset(Dataset):
    """Deterministic random images + labels; stands in for real datasets in
    tests/benchmarks (the reference tests use fake readers the same way)."""

    def __init__(self, num_samples=128, image_shape=(3, 32, 32),
                 num_classes=10, seed=0, transform=None):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(num_samples, *image_shape) \
            .astype(np.float32)
        self.labels = rng.randint(0, num_classes,
                                  size=(num_samples, 1)).astype(np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[0:4], "big")
    if magic != 2051:
        raise ValueError(f"{path}: not an IDX image file (magic {magic})")
    n = int.from_bytes(data[4:8], "big")
    rows = int.from_bytes(data[8:12], "big")
    cols = int.from_bytes(data[12:16], "big")
    arr = np.frombuffer(data, np.uint8, offset=16)
    return arr.reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[0:4], "big")
    if magic != 2049:
        raise ValueError(f"{path}: not an IDX label file (magic {magic})")
    return np.frombuffer(data, np.uint8, offset=8)


class MNIST(Dataset):
    """Reference mnist.py: idx-format images/labels; mode train|test.
    Files must exist locally (image_path/label_path) — no download here."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download:
            raise RuntimeError(
                f"{self.NAME}: download is unavailable in this environment "
                "(no network egress); pass local image_path/label_path")
        if image_path is None or label_path is None:
            raise ValueError(
                f"{self.NAME}: image_path and label_path are required "
                "(auto-download is unsupported without egress)")
        for p in (image_path, label_path):
            if not os.path.exists(p):
                raise FileNotFoundError(p)
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        if len(self.images) != len(self.labels):
            raise ValueError("image/label count mismatch")
        self.mode = mode
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i].astype(np.float32)[None]  # [1, 28, 28]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[i]], np.int64)


class FashionMNIST(MNIST):
    """Reference fashion-mnist (same idx format)."""

    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """Reference cifar.py: the python-pickle batches inside the official
    tar.gz; mode train|test."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download:
            raise RuntimeError(
                "Cifar: download is unavailable in this environment "
                "(no network egress); pass a local data_file tar.gz")
        if data_file is None:
            raise ValueError("Cifar: data_file (the official tar.gz) is "
                             "required")
        wanted = self._train_members if mode == "train" \
            else self._test_members
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tar:
            for m in tar.getmembers():
                base = os.path.basename(m.name)
                if base in wanted:
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    images.append(np.asarray(d[b"data"], np.uint8))
                    labels.append(np.asarray(d[self._label_key],
                                             np.int64))
        if not images:
            raise ValueError(f"no {mode} batches found in {data_file}")
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.concatenate(labels)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[i]], np.int64)


class Cifar100(Cifar10):
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"


# -- folder datasets (reference vision/datasets/folder.py) -------------------

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                  ".tif", ".tiff", ".webp")


def has_valid_extension(filename, extensions=IMG_EXTENSIONS):
    """reference folder.py has_valid_extension."""
    return filename.lower().endswith(tuple(extensions))


def default_loader(path, backend="pil"):
    """reference folder.py default_loader (pil backend; cv2 falls back
    to PIL+numpy since opencv isn't in this image)."""
    from PIL import Image

    with open(path, "rb") as f:
        img = Image.open(f)
        img = img.convert("RGB")
    if backend == "cv2":
        return np.asarray(img)[:, :, ::-1]  # BGR like cv2.imread
    return img


def make_dataset(directory, class_to_idx, extensions=None,
                 is_valid_file=None):
    """reference folder.py make_dataset: walk class subdirs, return
    (path, class_index) samples."""
    if (extensions is None) == (is_valid_file is None):
        raise ValueError("both extensions and is_valid_file cannot be "
                         "None or not None at the same time")
    if is_valid_file is None:
        def is_valid_file(p):
            return has_valid_extension(p, extensions)

    instances = []
    directory = os.path.expanduser(directory)
    for target_class in sorted(class_to_idx.keys()):
        class_index = class_to_idx[target_class]
        target_dir = os.path.join(directory, target_class)
        if not os.path.isdir(target_dir):
            continue
        for root, _, fnames in sorted(os.walk(target_dir,
                                              followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    instances.append((path, class_index))
    return instances


class DatasetFolder(Dataset):
    """Generic folder-of-class-subfolders dataset (reference
    vision/datasets/folder.py:90): root/class_x/xxx.png."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes, class_to_idx = self._find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions,
                               is_valid_file)
        if len(samples) == 0:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\n"
                f"Supported extensions are: "
                f"{','.join(extensions or [])}")
        self.loader = loader if loader is not None else default_loader
        self.extensions = extensions
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]
        self.dtype = "float32"

    @staticmethod
    def _find_classes(directory):
        classes = sorted(e.name for e in os.scandir(directory)
                         if e.is_dir())
        if not classes:
            raise FileNotFoundError(
                f"Couldn't find any class folder in {directory}.")
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive folder of images, no labels (reference
    vision/datasets/folder.py:342)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return has_valid_extension(p, extensions)
        samples = []
        for r, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(r, fname)
                if is_valid_file(path):
                    samples.append(path)
        if len(samples) == 0:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\n"
                f"Supported extensions are: "
                f"{','.join(extensions or [])}")
        self.loader = loader if loader is not None else default_loader
        self.extensions = extensions
        self.samples = samples
        self.dtype = "float32"

    def __getitem__(self, index):
        path = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """VOC2012 segmentation from the devkit tar (reference
    vision/datasets/voc2012.py; download unsupported here — pass
    data_file)."""

    MODE_FLAG_MAP = {"train": "trainval", "test": "train",
                     "valid": "val"}
    SET_FILE = ("VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt")
    DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if mode.lower() not in ("train", "valid", "test"):
            raise AssertionError(
                f"mode should be 'train', 'valid' or 'test', "
                f"but got {mode}")
        if data_file is None:
            raise ValueError(
                "data_file must point at the local VOCtrainval tar "
                "(downloading is unsupported in this environment)")
        self.flag = self.MODE_FLAG_MAP[mode.lower()]
        self.data_file = data_file
        self.transform = transform
        self._load_anno()
        self.dtype = "float32"

    def _load_anno(self):
        self.name2mem = {}
        self.data_tar = tarfile.open(self.data_file)
        for ele in self.data_tar.getmembers():
            self.name2mem[ele.name] = ele
        set_file = self.SET_FILE.format(self.flag)
        sets = self.data_tar.extractfile(self.name2mem[set_file])
        self.data = []
        self.labels = []
        for line in sets:
            line = line.strip().decode("utf-8")
            self.data.append(self.DATA_FILE.format(line))
            self.labels.append(self.LABEL_FILE.format(line))

    def __getitem__(self, idx):
        from PIL import Image

        data_file = self.data[idx]
        label_file = self.labels[idx]
        data = np.asarray(Image.open(
            self.data_tar.extractfile(self.name2mem[data_file])))
        label = np.asarray(Image.open(
            self.data_tar.extractfile(self.name2mem[label_file])))
        if self.transform is not None:
            data = self.transform(data)
        return data.astype(self.dtype), label.astype("int64")

    def __len__(self):
        return len(self.data)


class Flowers(Dataset):
    """Oxford 102 flowers (reference vision/datasets/flowers.py;
    download unsupported here — pass data_file/label_file/setid_file)."""

    # train uses the (larger) tstid split, mirroring the reference
    # flowers.py:51 MODE_FLAG_MAP.
    MODE_FLAG_MAP = {"train": "tstid", "test": "trnid",
                     "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        if mode.lower() not in ("train", "valid", "test"):
            raise AssertionError(
                f"mode should be 'train', 'valid' or 'test', "
                f"but got {mode}")
        if not (data_file and label_file and setid_file):
            raise ValueError(
                "data_file, label_file and setid_file must point at "
                "local copies (downloading is unsupported in this "
                "environment)")
        if backend is None:
            backend = "pil"
        if backend not in ("pil", "cv2"):
            raise ValueError(
                f"Expected backend are one of ['pil', 'cv2'], "
                f"but got {backend}")
        import scipy.io as sio

        self.backend = backend
        self.flag = self.MODE_FLAG_MAP[mode.lower()]
        self.transform = transform
        self.data_tar = tarfile.open(data_file)
        self.name2mem = {e.name: e for e in self.data_tar.getmembers()}
        self.labels = sio.loadmat(label_file)["labels"][0]
        self.indexes = sio.loadmat(setid_file)[self.flag][0]
        self.dtype = "float32"

    def __getitem__(self, idx):
        from PIL import Image

        index = int(self.indexes[idx])
        label = int(self.labels[index - 1])
        img_name = "jpg/image_%05d.jpg" % index
        # pil backend hands the transform a PIL Image, matching the
        # reference flowers.py (cv2 gets a BGR ndarray).
        img = Image.open(
            self.data_tar.extractfile(self.name2mem[img_name]))
        if self.backend == "cv2":
            img = np.asarray(img)[:, :, ::-1]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label]).astype("int64")

    def __len__(self):
        return len(self.indexes)
