"""Vision datasets.

Reference: ``python/paddle/vision/datasets/`` (MNIST mnist.py, Cifar
cifar.py, FashionMNIST).  Same file formats and __getitem__ contracts;
`download=True` is unsupported in this environment (no egress) — point
``image_path``/``data_file`` at local copies, or use FakeImageDataset for
pipeline work without data on disk.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ..io import Dataset


class FakeImageDataset(Dataset):
    """Deterministic random images + labels; stands in for real datasets in
    tests/benchmarks (the reference tests use fake readers the same way)."""

    def __init__(self, num_samples=128, image_shape=(3, 32, 32),
                 num_classes=10, seed=0, transform=None):
        rng = np.random.RandomState(seed)
        self.images = rng.rand(num_samples, *image_shape) \
            .astype(np.float32)
        self.labels = rng.randint(0, num_classes,
                                  size=(num_samples, 1)).astype(np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[0:4], "big")
    if magic != 2051:
        raise ValueError(f"{path}: not an IDX image file (magic {magic})")
    n = int.from_bytes(data[4:8], "big")
    rows = int.from_bytes(data[8:12], "big")
    cols = int.from_bytes(data[12:16], "big")
    arr = np.frombuffer(data, np.uint8, offset=16)
    return arr.reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[0:4], "big")
    if magic != 2049:
        raise ValueError(f"{path}: not an IDX label file (magic {magic})")
    return np.frombuffer(data, np.uint8, offset=8)


class MNIST(Dataset):
    """Reference mnist.py: idx-format images/labels; mode train|test.
    Files must exist locally (image_path/label_path) — no download here."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download:
            raise RuntimeError(
                f"{self.NAME}: download is unavailable in this environment "
                "(no network egress); pass local image_path/label_path")
        if image_path is None or label_path is None:
            raise ValueError(
                f"{self.NAME}: image_path and label_path are required "
                "(auto-download is unsupported without egress)")
        for p in (image_path, label_path):
            if not os.path.exists(p):
                raise FileNotFoundError(p)
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        if len(self.images) != len(self.labels):
            raise ValueError("image/label count mismatch")
        self.mode = mode
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i].astype(np.float32)[None]  # [1, 28, 28]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[i]], np.int64)


class FashionMNIST(MNIST):
    """Reference fashion-mnist (same idx format)."""

    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """Reference cifar.py: the python-pickle batches inside the official
    tar.gz; mode train|test."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download:
            raise RuntimeError(
                "Cifar: download is unavailable in this environment "
                "(no network egress); pass a local data_file tar.gz")
        if data_file is None:
            raise ValueError("Cifar: data_file (the official tar.gz) is "
                             "required")
        wanted = self._train_members if mode == "train" \
            else self._test_members
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tar:
            for m in tar.getmembers():
                base = os.path.basename(m.name)
                if base in wanted:
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    images.append(np.asarray(d[b"data"], np.uint8))
                    labels.append(np.asarray(d[self._label_key],
                                             np.int64))
        if not images:
            raise ValueError(f"no {mode} batches found in {data_file}")
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.concatenate(labels)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[i]], np.int64)


class Cifar100(Cifar10):
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"
