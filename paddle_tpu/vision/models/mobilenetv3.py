"""MobileNetV3 Small/Large (reference:
python/paddle/vision/models/mobilenetv3.py).

Inverted residuals with optional squeeze-excite, hardswish/relu
activations; channel counts snapped to multiples of 8 as in the paper.
"""
from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcitation(nn.Layer):
    def __init__(self, channels, squeeze_channels):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(channels, squeeze_channels, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_channels, channels, 1)
        self.hard = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hard(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * s


class InvertedResidual(nn.Layer):
    def __init__(self, in_ch, exp_ch, out_ch, kernel, stride, use_se,
                 act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp_ch != in_ch:
            layers += [nn.Conv2D(in_ch, exp_ch, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_ch), act_layer()]
        # reference block order: dw-conv -> BN -> act -> SE -> pw-conv
        layers += [nn.Conv2D(exp_ch, exp_ch, kernel, stride=stride,
                             padding=kernel // 2, groups=exp_ch,
                             bias_attr=False),
                   nn.BatchNorm2D(exp_ch), act_layer()]
        if use_se:
            layers.append(SqueezeExcitation(
                exp_ch, _make_divisible(exp_ch // 4)))
        layers += [nn.Conv2D(exp_ch, out_ch, 1, bias_attr=False),
                   nn.BatchNorm2D(out_ch)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, use_se, act, stride)
_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_ch = _make_divisible(16 * scale)
        self.conv = nn.Sequential(
            nn.Conv2D(3, in_ch, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_ch), nn.Hardswish())
        blocks = []
        for k, exp, out, se, act, s in config:
            exp_ch = _make_divisible(exp * scale)
            out_ch = _make_divisible(out * scale)
            blocks.append(InvertedResidual(in_ch, exp_ch, out_ch, k, s,
                                           se, act))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        # in_ch is already scale-adjusted; 6x expansion only.
        last_conv = _make_divisible(6 * in_ch)
        self.lastconv = nn.Sequential(
            nn.Conv2D(in_ch, last_conv, 1, bias_attr=False),
            nn.BatchNorm2D(last_conv), nn.Hardswish())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        from ... import ops

        x = self.lastconv(self.blocks(self.conv(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)
