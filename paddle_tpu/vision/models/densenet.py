"""DenseNet (reference: python/paddle/vision/models/densenet.py).

Dense blocks concatenate every prior layer's features; growth_rate new
channels per layer, halving transition layers between blocks.
"""
from __future__ import annotations

from ... import nn, ops


class DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        return ops.concat([x, out], axis=1)


class DenseBlock(nn.Layer):
    def __init__(self, num_layers, in_ch, growth_rate, bn_size):
        super().__init__()
        self.layers = nn.LayerList([
            DenseLayer(in_ch + i * growth_rate, growth_rate, bn_size)
            for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


_CFG = {
    121: (32, [6, 12, 24, 16]), 161: (48, [6, 12, 36, 24]),
    169: (32, [6, 12, 32, 32]), 201: (32, [6, 12, 48, 32]),
    264: (32, [6, 12, 64, 48]),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        growth_rate, block_cfg = _CFG[layers]
        num_init = 2 * growth_rate

        self.conv1 = nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                               bias_attr=False)
        self.norm1 = nn.BatchNorm2D(num_init)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)

        blocks = []
        ch = num_init
        for i, num_layers in enumerate(block_cfg):
            blocks.append(DenseBlock(num_layers, ch, growth_rate,
                                     bn_size))
            ch += num_layers * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(Transition(ch, ch // 2))
                ch //= 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(ch)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.norm1(self.conv1(x))))
        x = self.relu(self.norm_final(self.blocks(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)
