"""LeNet and AlexNet.

Reference: ``python/paddle/vision/models/lenet.py`` and ``alexnet.py``.
"""
from __future__ import annotations

from ... import nn, ops


class LeNet(nn.Layer):
    """Reference lenet.py — MNIST-scale convnet (1x28x28 inputs)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = ops.reshape(x, [x.shape[0], -1])
            x = self.fc(x)
        return x


class AlexNet(nn.Layer):
    """Reference alexnet.py (224x224 inputs)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = ops.reshape(x, [x.shape[0], -1])
            x = self.classifier(x)
        return x


def alexnet(**kwargs):
    return AlexNet(**kwargs)
