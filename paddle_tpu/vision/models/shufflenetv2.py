"""ShuffleNetV2 (reference:
python/paddle/vision/models/shufflenetv2.py).

Channel shuffle is a reshape+transpose — XLA fuses it into the
surrounding elementwise work, so it costs nothing on TPU.
"""
from __future__ import annotations

from ... import nn, ops


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = ops.reshape(x, [n, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [n, c, h, w])


def _act_layer(act):
    return nn.Swish() if act == "swish" else nn.ReLU()


def _conv_bn_act(in_ch, out_ch, kernel, stride=1, groups=1, act="relu"):
    layers = [nn.Conv2D(in_ch, out_ch, kernel, stride=stride,
                        padding=kernel // 2, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(out_ch)]
    if act is not None:
        layers.append(_act_layer(act))
    return nn.Sequential(*layers)


class InvertedResidual(nn.Layer):
    """Stride-1 unit: split channels, transform one branch, shuffle."""

    def __init__(self, channels, act="relu"):
        super().__init__()
        half = channels // 2
        self.branch = nn.Sequential(
            _conv_bn_act(half, half, 1, act=act),
            _conv_bn_act(half, half, 3, groups=half, act=None),
            _conv_bn_act(half, half, 1, act=act))

    def forward(self, x):
        half = x.shape[1] // 2
        x1 = x[:, :half]
        x2 = x[:, half:]
        out = ops.concat([x1, self.branch(x2)], axis=1)
        return channel_shuffle(out, 2)


class InvertedResidualDS(nn.Layer):
    """Stride-2 (downsampling) unit: both branches transform."""

    def __init__(self, in_ch, out_ch, act="relu"):
        super().__init__()
        half = out_ch // 2
        self.branch1 = nn.Sequential(
            _conv_bn_act(in_ch, in_ch, 3, stride=2, groups=in_ch,
                         act=None),
            _conv_bn_act(in_ch, half, 1, act=act))
        self.branch2 = nn.Sequential(
            _conv_bn_act(in_ch, half, 1, act=act),
            _conv_bn_act(half, half, 3, stride=2, groups=half, act=None),
            _conv_bn_act(half, half, 1, act=act))

    def forward(self, x):
        out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_out = _STAGE_OUT[scale]
        stage_repeats = [4, 8, 4]

        self.conv1 = _conv_bn_act(3, stage_out[0], 3, stride=2, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        in_ch = stage_out[0]
        for stage, repeats in enumerate(stage_repeats):
            out_ch = stage_out[stage + 1]
            blocks.append(InvertedResidualDS(in_ch, out_ch, act=act))
            for _ in range(repeats - 1):
                blocks.append(InvertedResidual(out_ch, act=act))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = _conv_bn_act(in_ch, stage_out[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(stage_out[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.blocks(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
