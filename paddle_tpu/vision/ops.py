"""Detection ops — nms, roi_align, roi_pool, box_coder.

Reference: ``python/paddle/vision/ops.py`` (nms:1936, roi_align:1707,
roi_pool:1574, box_coder:584; CUDA kernels under
``paddle/phi/kernels/gpu/``).

TPU-native design notes:
- ``nms`` eager returns kept INDICES with a data-dependent count
  (host numpy greedy suppression over an O(n²) IoU matrix, like the
  reference's CPU kernel).  Under a trace (jit.save / to_static /
  Predictor) it switches to an in-graph ``lax.fori_loop`` suppression
  returning a FIXED top_k-sized index vector padded with -1 — so
  detection models export end-to-end (r4).
- ``roi_align``/``roi_pool`` compute their sampling geometry on host
  (boxes are non-differentiable in the reference kernels too) and then
  perform ONE vectorized gather + segment reduction on device through
  the op registry — differentiable w.r.t. the feature map ``x``, and
  the bilinear-sample semantics (incl. adaptive sampling_ratio and the
  Detectron2 ``aligned`` half-pixel shift) match the reference kernel.
- ``box_coder`` is a pure elementwise chain (registry-dispatched).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry

_op = _registry.cached_apply


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


# -- nms --------------------------------------------------------------------

def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def _nms_single(boxes, iou_threshold, order):
    iou = _iou_matrix(boxes)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True  # self-IoU is 1; keep it once
    return np.array(keep, np.int64)


def _nms_device(boxes, scores, iou_threshold, max_out):
    """Greedy NMS as ONE compiled program (lax.fori_loop, static
    ``max_out`` outputs padded with -1) — VERDICT r3 weak #5: the
    host-numpy nms broke any detection model exported through
    jit.save/Predictor.  O(max_out * n) IoU rows; n static.

    Matches the host `_nms_single` ordering exactly: highest score
    first, ties broken by lower index (stable sort order)."""
    n = boxes.shape[0]
    x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2],
                      boxes[:, 3])
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)

    def body(i, carry):
        keep, live, s = carry
        # lowest index wins ties, like np.argsort(kind='stable')
        idx = jnp.argmax(s)
        valid = s[idx] > neg_inf
        keep = keep.at[i].set(jnp.where(valid, idx, -1))
        ix1 = jnp.maximum(x1[idx], x1)
        iy1 = jnp.maximum(y1[idx], y1)
        ix2 = jnp.minimum(x2[idx], x2)
        iy2 = jnp.minimum(y2[idx], y2)
        inter = (jnp.maximum(ix2 - ix1, 0)
                 * jnp.maximum(iy2 - iy1, 0))
        iou = inter / jnp.maximum(area[idx] + area - inter, 1e-10)
        suppress = (iou > iou_threshold) | (
            jnp.arange(n) == idx)
        suppress = jnp.where(valid, suppress, False)
        live = live & ~suppress
        s = jnp.where(live, s, neg_inf)
        return keep, live, s

    keep0 = jnp.full((max_out,), -1, jnp.int64)
    live0 = jnp.ones((n,), bool)
    keep, _, _ = jax.lax.fori_loop(
        0, max_out, body, (keep0, live0, scores.astype(jnp.float32)))
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference vision/ops.py:1936.  Returns kept box indices; with
    ``scores`` boxes are processed high-score-first; with categories the
    suppression is per-category (batched NMS via the coordinate-offset
    trick) and results are score-sorted.

    Compiled path: when inputs are traced (inside jit/to_static — e.g.
    a detection model exported via jit.save and served by the
    Predictor) the suppression runs in-graph via ``lax.fori_loop`` and
    returns a FIXED-size index vector of length ``top_k`` (required
    when traced) padded with -1."""
    b_raw = boxes._data if isinstance(boxes, Tensor) else boxes
    traced = isinstance(b_raw, jax.core.Tracer) or any(
        isinstance(getattr(t, "_data", t), jax.core.Tracer)
        for t in (scores, category_idxs) if t is not None)
    if traced:
        if top_k is None:
            raise ValueError(
                "nms under jit needs top_k (static output size); got "
                "top_k=None")
        bj = jnp.asarray(b_raw, jnp.float32)
        sj = (jnp.asarray(getattr(scores, "_data", scores),
                          jnp.float32) if scores is not None
              else -jnp.arange(bj.shape[0], dtype=jnp.float32))
        if category_idxs is not None:
            cats = jnp.asarray(getattr(category_idxs, "_data",
                                       category_idxs))
            span = (jnp.max(bj[:, 2:]) - jnp.min(bj[:, :2])) + 1.0
            bj = bj + (cats.astype(jnp.float32) * span)[:, None]
        return Tensor(_nms_device(bj, sj, float(iou_threshold),
                                  int(top_k)))
    b = _np(boxes).astype(np.float64)
    n = b.shape[0]
    if scores is None:
        if category_idxs is not None:
            cats = _np(category_idxs).astype(np.int64)
            span = (b[:, 2:].max() - b[:, :2].min()) + 1.0
            b = b + (cats * span)[:, None]
        keep = _nms_single(b, iou_threshold, np.arange(n))
        if top_k is not None:
            keep = keep[:top_k]
        return Tensor(jnp.asarray(keep))
    s = _np(scores).astype(np.float64)
    if category_idxs is None:
        order = np.argsort(-s, kind="stable")
        keep = _nms_single(b, iou_threshold, order)
    else:
        cats = _np(category_idxs).astype(np.int64)
        # Offset boxes per category so cross-category IoU is 0.
        span = (b[:, 2:].max() - b[:, :2].min()) + 1.0
        shifted = b + (cats * span)[:, None]
        order = np.argsort(-s, kind="stable")
        keep = _nms_single(shifted, iou_threshold, order)
        keep = keep[np.argsort(-s[keep], kind="stable")]
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


# -- roi align / pool -------------------------------------------------------

def _roi_batch_ids(boxes_num, n_rois):
    bn = _np(boxes_num).astype(np.int64)
    ids = np.repeat(np.arange(len(bn)), bn)
    assert len(ids) == n_rois, (len(ids), n_rois)
    return ids


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference vision/ops.py:1707 (Mask R-CNN RoIAlign, Detectron2
    ``aligned`` semantics).  Differentiable w.r.t. ``x``."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    b = _np(boxes).astype(np.float64)
    n_rois = b.shape[0]
    H, W = _np(x).shape[2:]
    batch_ids = _roi_batch_ids(boxes_num, n_rois)

    off = 0.5 if aligned else 0.0
    sb, sy, sx, bin_id, inv_cnt = [], [], [], [], []
    for r in range(n_rois):
        x1, y1, x2, y2 = b[r] * spatial_scale
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:  # legacy: force minimum size 1
            rw = max(rw, 1.0)
            rh = max(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        gy = sampling_ratio if sampling_ratio > 0 \
            else max(1, math.ceil(rh / ph))
        gx = sampling_ratio if sampling_ratio > 0 \
            else max(1, math.ceil(rw / pw))
        for by in range(ph):
            for bx in range(pw):
                bid = (r * ph + by) * pw + bx
                for iy in range(gy):
                    yy = y1 + by * bin_h + (iy + 0.5) * bin_h / gy
                    for ix in range(gx):
                        xx = x1 + bx * bin_w + (ix + 0.5) * bin_w / gx
                        sb.append(batch_ids[r])
                        sy.append(yy)
                        sx.append(xx)
                        bin_id.append(bid)
                        inv_cnt.append(1.0 / (gy * gx))

    sb = jnp.asarray(np.array(sb, np.int32))
    sy = jnp.asarray(np.array(sy, np.float32))
    sx = jnp.asarray(np.array(sx, np.float32))
    bin_id = jnp.asarray(np.array(bin_id, np.int32))
    inv_cnt = jnp.asarray(np.array(inv_cnt, np.float32))
    n_bins = n_rois * ph * pw

    def fn(x, sb, sy, sx, bin_id, inv_cnt, n_bins, ph, pw):
        N, C, H, W = x.shape
        # Bilinear sample, zero outside [-1, H) as the reference kernel.
        valid = ((sy > -1.0) & (sy < H) & (sx > -1.0) & (sx < W))
        yc = jnp.clip(sy, 0.0, H - 1)
        xc = jnp.clip(sx, 0.0, W - 1)
        y0 = jnp.floor(yc).astype(jnp.int32)
        x0 = jnp.floor(xc).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        ly = yc - y0
        lx = xc - x0
        w00 = (1 - ly) * (1 - lx)
        w01 = (1 - ly) * lx
        w10 = ly * (1 - lx)
        w11 = ly * lx
        # [S, C] gathers
        g = (x[sb, :, y0, x0] * w00[:, None]
             + x[sb, :, y0, x1] * w01[:, None]
             + x[sb, :, y1, x0] * w10[:, None]
             + x[sb, :, y1, x1] * w11[:, None])
        g = g * (valid.astype(g.dtype) * inv_cnt)[:, None]
        pooled = jax.ops.segment_sum(g, bin_id, num_segments=n_bins)
        out = pooled.reshape(-1, ph, pw, pooled.shape[-1])
        return jnp.transpose(out, (0, 3, 1, 2))

    return _op("roi_align", fn, x, sb, sy, sx, bin_id, inv_cnt,
               n_bins=n_bins, ph=ph, pw=pw)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Reference vision/ops.py:1574 (max-pool per bin, Fast R-CNN)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    b = _np(boxes).astype(np.float64)
    n_rois = b.shape[0]
    H, W = _np(x).shape[2:]
    batch_ids = _roi_batch_ids(boxes_num, n_rois)

    sb, syi, sxi, bin_id = [], [], [], []
    for r in range(n_rois):
        x1 = int(round(b[r, 0] * spatial_scale))
        y1 = int(round(b[r, 1] * spatial_scale))
        x2 = int(round(b[r, 2] * spatial_scale))
        y2 = int(round(b[r, 3] * spatial_scale))
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        bin_h = rh / ph
        bin_w = rw / pw
        for by in range(ph):
            ys = int(np.floor(y1 + by * bin_h))
            ye = int(np.ceil(y1 + (by + 1) * bin_h))
            ys, ye = min(max(ys, 0), H), min(max(ye, 0), H)
            for bx in range(pw):
                xs = int(np.floor(x1 + bx * bin_w))
                xe = int(np.ceil(x1 + (bx + 1) * bin_w))
                xs, xe = min(max(xs, 0), W), min(max(xe, 0), W)
                bid = (r * ph + by) * pw + bx
                if ye <= ys or xe <= xs:  # empty bin -> contributes 0
                    sb.append(batch_ids[r])
                    syi.append(0)
                    sxi.append(0)
                    bin_id.append(bid + (n_rois * ph * pw))  # dump slot
                    continue
                for yy in range(ys, ye):
                    for xx in range(xs, xe):
                        sb.append(batch_ids[r])
                        syi.append(yy)
                        sxi.append(xx)
                        bin_id.append(bid)

    sb = jnp.asarray(np.array(sb, np.int32))
    syi = jnp.asarray(np.array(syi, np.int32))
    sxi = jnp.asarray(np.array(sxi, np.int32))
    bin_id = jnp.asarray(np.array(bin_id, np.int32))
    n_bins = n_rois * ph * pw

    def fn(x, sb, syi, sxi, bin_id, n_bins, ph, pw):
        g = x[sb, :, syi, sxi]  # [S, C]
        pooled = jax.ops.segment_max(g, bin_id,
                                     num_segments=2 * n_bins)[:n_bins]
        pooled = jnp.where(jnp.isfinite(pooled), pooled, 0.0)
        out = pooled.reshape(-1, ph, pw, pooled.shape[-1])
        return jnp.transpose(out, (0, 3, 1, 2))

    return _op("roi_pool", fn, x, sb, syi, sxi, bin_id,
               n_bins=n_bins, ph=ph, pw=pw)


# -- box coder --------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Reference vision/ops.py:584 — encode/decode center-size deltas."""
    norm = 0.0 if box_normalized else 1.0
    if isinstance(prior_box_var, (list, tuple)):
        var = jnp.asarray(np.array(prior_box_var, np.float32))
        var_is_tensor = False
    else:
        var = prior_box_var
        var_is_tensor = True

    if code_type == "encode_center_size":
        def fn(p, v, t, norm):
            pw = p[:, 2] - p[:, 0] + norm
            ph_ = p[:, 3] - p[:, 1] + norm
            px = p[:, 0] + pw * 0.5
            py = p[:, 1] + ph_ * 0.5
            tw = t[:, None, 2] - t[:, None, 0] + norm
            th = t[:, None, 3] - t[:, None, 1] + norm
            tx = t[:, None, 0] + tw * 0.5
            ty = t[:, None, 1] + th * 0.5
            v = jnp.broadcast_to(v.reshape(-1, 4) if v.ndim == 1
                                 else v, p.shape)
            ox = (tx - px[None, :]) / pw[None, :] / v[None, :, 0]
            oy = (ty - py[None, :]) / ph_[None, :] / v[None, :, 1]
            ow = jnp.log(jnp.abs(tw / pw[None, :])) / v[None, :, 2]
            oh = jnp.log(jnp.abs(th / ph_[None, :])) / v[None, :, 3]
            return jnp.stack([ox, oy, ow, oh], axis=-1)

        return _op("box_encode", fn, prior_box, var, target_box,
                   norm=norm)

    if code_type == "decode_center_size":
        def fn(p, v, t, norm, axis):
            if p.ndim == 2:
                p = jnp.expand_dims(p, axis)  # [1,M,4] or [N,1,4]
            vv = v
            if vv.ndim == 1:
                vv = jnp.broadcast_to(vv, p.shape)
            elif vv.ndim == 2:
                vv = jnp.expand_dims(vv, axis)
                vv = jnp.broadcast_to(vv, (t.shape[0],) + p.shape[1:]) \
                    if p.shape[0] == 1 else vv
            pw = p[..., 2] - p[..., 0] + norm
            ph_ = p[..., 3] - p[..., 1] + norm
            px = p[..., 0] + pw * 0.5
            py = p[..., 1] + ph_ * 0.5
            ox = vv[..., 0] * t[..., 0] * pw + px
            oy = vv[..., 1] * t[..., 1] * ph_ + py
            ow = jnp.exp(vv[..., 2] * t[..., 2]) * pw
            oh = jnp.exp(vv[..., 3] * t[..., 3]) * ph_
            return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                              ox + ow * 0.5 - norm,
                              oy + oh * 0.5 - norm], axis=-1)

        return _op("box_decode", fn, prior_box, var, target_box,
                   norm=norm, axis=int(axis))

    raise ValueError(f"unknown code_type {code_type!r}")


def _bilinear_sample(x, fy, fx):
    """x [B, C, H, W]; fy/fx [B, ...] float coords -> [B, C, ...]
    bilinear samples, zeros outside."""
    import jax

    B, C, H, W = x.shape

    def gather(iy, ix):
        inb = ((iy >= 0) & (iy < H) & (ix >= 0) & (ix < W))
        iyc = jnp.clip(iy, 0, H - 1)
        ixc = jnp.clip(ix, 0, W - 1)
        vals = jax.vmap(lambda img, jy, jx: img[:, jy, jx])(x, iyc, ixc)
        return vals * inb[:, None].astype(x.dtype)

    y0 = jnp.floor(fy).astype(jnp.int32)
    x0 = jnp.floor(fx).astype(jnp.int32)
    wy = (fy - y0)[:, None].astype(x.dtype)
    wx = (fx - x0)[:, None].astype(x.dtype)
    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference vision/ops.py
    deform_conv2d; phi deformable_conv kernel).

    x [B, Cin, H, W]; offset [B, 2*dg*Kh*Kw, Ho, Wo] as (dy, dx) pairs
    per tap; mask [B, dg*Kh*Kw, Ho, Wo] (v2 modulation) or None (v1).

    TPU-native: each kernel tap is a bilinear gather at its offset
    position; the taps stack into [B, Cin*Kh*Kw, Ho, Wo] and ONE einsum
    against the weight does the contraction on the MXU.
    """
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else dilation

    def fn(x, offset, weight, mask, sh, sw, ph, pw, dh, dw, dg, groups,
           has_mask):
        B, Cin, H, W = x.shape
        Cout, Cin_g, Kh, Kw = weight.shape
        Ho = (H + 2 * ph - (dh * (Kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (Kw - 1) + 1)) // sw + 1
        K = Kh * Kw
        off = offset.reshape(B, dg, K, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, :]
        ky = (jnp.arange(Kh) * dh)[:, None].repeat(Kw, 1).reshape(K)
        kx = (jnp.arange(Kw) * dw)[None, :].repeat(Kh, 0).reshape(K)
        cg = Cin // dg
        samples = []
        for g in range(dg):
            fy = (base_y + ky[:, None, None]
                  + off[:, g, :, 0])                   # [B, K, Ho, Wo]
            fx = base_x + kx[:, None, None] + off[:, g, :, 1]
            xs = x[:, g * cg:(g + 1) * cg]
            s = _bilinear_sample(
                xs, fy.reshape(B, -1), fx.reshape(B, -1)).reshape(
                B, cg, K, Ho, Wo)
            if has_mask:
                s = s * mask.reshape(B, dg, K, Ho, Wo)[:, g][:, None]
            samples.append(s)
        sampled = jnp.concatenate(samples, axis=1)  # [B, Cin, K, Ho, Wo]
        # grouped contraction: [B, Cin, K, Ho, Wo] x [Cout, Cin/g, K]
        w2 = weight.reshape(Cout, Cin_g, K)
        if groups == 1:
            out = jnp.einsum("bckhw,ock->bohw", sampled, w2)
        else:
            co_g = Cout // groups
            outs = []
            for g in range(groups):
                outs.append(jnp.einsum(
                    "bckhw,ock->bohw",
                    sampled[:, g * Cin_g:(g + 1) * Cin_g],
                    w2[g * co_g:(g + 1) * co_g]))
            out = jnp.concatenate(outs, axis=1)
        return out

    out = _op("deform_conv2d", fn, _t_in(x), _t_in(offset), _t_in(weight),
              _t_in(mask) if mask is not None else _t_in(
                  jnp.zeros((1,), jnp.float32)),
              sh=int(sh), sw=int(sw), ph=int(ph), pw=int(pw),
              dh=int(dh), dw=int(dw), dg=int(deformable_groups),
              groups=int(groups), has_mask=mask is not None)
    if bias is not None:
        from ..ops import reshape as _rs

        out = out + _rs(_t_in(bias), [1, -1, 1, 1])
    return out


def _t_in(v):
    from ..core.tensor import Tensor

    return v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))


from ..nn import initializer as _I  # noqa: E402
from ..nn.layers import Layer as _Layer  # noqa: E402


class DeformConv2D(_Layer):
    """Layer form (reference vision/ops.py DeformConv2D): the caller
    supplies offset (and mask for v2) at forward time."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1,
                 deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) \
            if isinstance(kernel_size, int) else kernel_size
        self._cfg = (stride, padding, dilation, deformable_groups,
                     groups)
        bound = 1.0 / math.sqrt(in_channels * kh * kw)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr,
            default_initializer=_I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=_I.Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        stride, padding, dilation, dg, groups = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=stride, padding=padding,
                             dilation=dilation,
                             deformable_groups=dg, groups=groups,
                             mask=mask)
