"""Detection ops — nms, roi_align, roi_pool, box_coder.

Reference: ``python/paddle/vision/ops.py`` (nms:1936, roi_align:1707,
roi_pool:1574, box_coder:584; CUDA kernels under
``paddle/phi/kernels/gpu/``).

TPU-native design notes:
- ``nms`` eager returns kept INDICES with a data-dependent count
  (host numpy greedy suppression over an O(n²) IoU matrix, like the
  reference's CPU kernel).  Under a trace (jit.save / to_static /
  Predictor) it switches to an in-graph ``lax.fori_loop`` suppression
  returning a FIXED top_k-sized index vector padded with -1 — so
  detection models export end-to-end (r4).
- ``roi_align``/``roi_pool`` compute their sampling geometry on host
  (boxes are non-differentiable in the reference kernels too) and then
  perform ONE vectorized gather + segment reduction on device through
  the op registry — differentiable w.r.t. the feature map ``x``, and
  the bilinear-sample semantics (incl. adaptive sampling_ratio and the
  Detectron2 ``aligned`` half-pixel shift) match the reference kernel.
- ``box_coder`` is a pure elementwise chain (registry-dispatched).
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import registry as _registry

_op = _registry.cached_apply


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


# -- nms --------------------------------------------------------------------

def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def _nms_single(boxes, iou_threshold, order):
    iou = _iou_matrix(boxes)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True  # self-IoU is 1; keep it once
    return np.array(keep, np.int64)


def _nms_device(boxes, scores, iou_threshold, max_out):
    """Greedy NMS as ONE compiled program (lax.fori_loop, static
    ``max_out`` outputs padded with -1) — VERDICT r3 weak #5: the
    host-numpy nms broke any detection model exported through
    jit.save/Predictor.  O(max_out * n) IoU rows; n static.

    Matches the host `_nms_single` ordering exactly: highest score
    first, ties broken by lower index (stable sort order)."""
    n = boxes.shape[0]
    x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2],
                      boxes[:, 3])
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)

    def body(i, carry):
        keep, live, s = carry
        # lowest index wins ties, like np.argsort(kind='stable')
        idx = jnp.argmax(s)
        valid = s[idx] > neg_inf
        keep = keep.at[i].set(jnp.where(valid, idx, -1))
        ix1 = jnp.maximum(x1[idx], x1)
        iy1 = jnp.maximum(y1[idx], y1)
        ix2 = jnp.minimum(x2[idx], x2)
        iy2 = jnp.minimum(y2[idx], y2)
        inter = (jnp.maximum(ix2 - ix1, 0)
                 * jnp.maximum(iy2 - iy1, 0))
        iou = inter / jnp.maximum(area[idx] + area - inter, 1e-10)
        suppress = (iou > iou_threshold) | (
            jnp.arange(n) == idx)
        suppress = jnp.where(valid, suppress, False)
        live = live & ~suppress
        s = jnp.where(live, s, neg_inf)
        return keep, live, s

    keep0 = jnp.full((max_out,), -1, jnp.int64)
    live0 = jnp.ones((n,), bool)
    keep, _, _ = jax.lax.fori_loop(
        0, max_out, body, (keep0, live0, scores.astype(jnp.float32)))
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference vision/ops.py:1936.  Returns kept box indices; with
    ``scores`` boxes are processed high-score-first; with categories the
    suppression is per-category (batched NMS via the coordinate-offset
    trick) and results are score-sorted.

    Compiled path: when inputs are traced (inside jit/to_static — e.g.
    a detection model exported via jit.save and served by the
    Predictor) the suppression runs in-graph via ``lax.fori_loop`` and
    returns a FIXED-size index vector of length ``top_k`` (required
    when traced) padded with -1."""
    b_raw = boxes._data if isinstance(boxes, Tensor) else boxes
    traced = isinstance(b_raw, jax.core.Tracer) or any(
        isinstance(getattr(t, "_data", t), jax.core.Tracer)
        for t in (scores, category_idxs) if t is not None)
    if traced:
        if top_k is None:
            raise ValueError(
                "nms under jit needs top_k (static output size); got "
                "top_k=None")
        bj = jnp.asarray(b_raw, jnp.float32)
        sj = (jnp.asarray(getattr(scores, "_data", scores),
                          jnp.float32) if scores is not None
              else -jnp.arange(bj.shape[0], dtype=jnp.float32))
        if category_idxs is not None:
            cats = jnp.asarray(getattr(category_idxs, "_data",
                                       category_idxs))
            span = (jnp.max(bj[:, 2:]) - jnp.min(bj[:, :2])) + 1.0
            bj = bj + (cats.astype(jnp.float32) * span)[:, None]
        return Tensor(_nms_device(bj, sj, float(iou_threshold),
                                  int(top_k)))
    b = _np(boxes).astype(np.float64)
    n = b.shape[0]
    if scores is None:
        if category_idxs is not None:
            cats = _np(category_idxs).astype(np.int64)
            span = (b[:, 2:].max() - b[:, :2].min()) + 1.0
            b = b + (cats * span)[:, None]
        keep = _nms_single(b, iou_threshold, np.arange(n))
        if top_k is not None:
            keep = keep[:top_k]
        return Tensor(jnp.asarray(keep))
    s = _np(scores).astype(np.float64)
    if category_idxs is None:
        order = np.argsort(-s, kind="stable")
        keep = _nms_single(b, iou_threshold, order)
    else:
        cats = _np(category_idxs).astype(np.int64)
        # Offset boxes per category so cross-category IoU is 0.
        span = (b[:, 2:].max() - b[:, :2].min()) + 1.0
        shifted = b + (cats * span)[:, None]
        order = np.argsort(-s, kind="stable")
        keep = _nms_single(shifted, iou_threshold, order)
        keep = keep[np.argsort(-s[keep], kind="stable")]
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


# -- roi align / pool -------------------------------------------------------

def _roi_batch_ids(boxes_num, n_rois):
    bn = _np(boxes_num).astype(np.int64)
    ids = np.repeat(np.arange(len(bn)), bn)
    assert len(ids) == n_rois, (len(ids), n_rois)
    return ids


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference vision/ops.py:1707 (Mask R-CNN RoIAlign, Detectron2
    ``aligned`` semantics).  Differentiable w.r.t. ``x``."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    b = _np(boxes).astype(np.float64)
    n_rois = b.shape[0]
    H, W = _np(x).shape[2:]
    batch_ids = _roi_batch_ids(boxes_num, n_rois)

    off = 0.5 if aligned else 0.0
    sb, sy, sx, bin_id, inv_cnt = [], [], [], [], []
    for r in range(n_rois):
        x1, y1, x2, y2 = b[r] * spatial_scale
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:  # legacy: force minimum size 1
            rw = max(rw, 1.0)
            rh = max(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        gy = sampling_ratio if sampling_ratio > 0 \
            else max(1, math.ceil(rh / ph))
        gx = sampling_ratio if sampling_ratio > 0 \
            else max(1, math.ceil(rw / pw))
        for by in range(ph):
            for bx in range(pw):
                bid = (r * ph + by) * pw + bx
                for iy in range(gy):
                    yy = y1 + by * bin_h + (iy + 0.5) * bin_h / gy
                    for ix in range(gx):
                        xx = x1 + bx * bin_w + (ix + 0.5) * bin_w / gx
                        sb.append(batch_ids[r])
                        sy.append(yy)
                        sx.append(xx)
                        bin_id.append(bid)
                        inv_cnt.append(1.0 / (gy * gx))

    sb = jnp.asarray(np.array(sb, np.int32))
    sy = jnp.asarray(np.array(sy, np.float32))
    sx = jnp.asarray(np.array(sx, np.float32))
    bin_id = jnp.asarray(np.array(bin_id, np.int32))
    inv_cnt = jnp.asarray(np.array(inv_cnt, np.float32))
    n_bins = n_rois * ph * pw

    def fn(x, sb, sy, sx, bin_id, inv_cnt, n_bins, ph, pw):
        N, C, H, W = x.shape
        # Bilinear sample, zero outside [-1, H) as the reference kernel.
        valid = ((sy > -1.0) & (sy < H) & (sx > -1.0) & (sx < W))
        yc = jnp.clip(sy, 0.0, H - 1)
        xc = jnp.clip(sx, 0.0, W - 1)
        y0 = jnp.floor(yc).astype(jnp.int32)
        x0 = jnp.floor(xc).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        ly = yc - y0
        lx = xc - x0
        w00 = (1 - ly) * (1 - lx)
        w01 = (1 - ly) * lx
        w10 = ly * (1 - lx)
        w11 = ly * lx
        # [S, C] gathers
        g = (x[sb, :, y0, x0] * w00[:, None]
             + x[sb, :, y0, x1] * w01[:, None]
             + x[sb, :, y1, x0] * w10[:, None]
             + x[sb, :, y1, x1] * w11[:, None])
        g = g * (valid.astype(g.dtype) * inv_cnt)[:, None]
        pooled = jax.ops.segment_sum(g, bin_id, num_segments=n_bins)
        out = pooled.reshape(-1, ph, pw, pooled.shape[-1])
        return jnp.transpose(out, (0, 3, 1, 2))

    return _op("roi_align", fn, x, sb, sy, sx, bin_id, inv_cnt,
               n_bins=n_bins, ph=ph, pw=pw)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Reference vision/ops.py:1574 (max-pool per bin, Fast R-CNN)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    b = _np(boxes).astype(np.float64)
    n_rois = b.shape[0]
    H, W = _np(x).shape[2:]
    batch_ids = _roi_batch_ids(boxes_num, n_rois)

    sb, syi, sxi, bin_id = [], [], [], []
    for r in range(n_rois):
        x1 = int(round(b[r, 0] * spatial_scale))
        y1 = int(round(b[r, 1] * spatial_scale))
        x2 = int(round(b[r, 2] * spatial_scale))
        y2 = int(round(b[r, 3] * spatial_scale))
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        bin_h = rh / ph
        bin_w = rw / pw
        for by in range(ph):
            ys = int(np.floor(y1 + by * bin_h))
            ye = int(np.ceil(y1 + (by + 1) * bin_h))
            ys, ye = min(max(ys, 0), H), min(max(ye, 0), H)
            for bx in range(pw):
                xs = int(np.floor(x1 + bx * bin_w))
                xe = int(np.ceil(x1 + (bx + 1) * bin_w))
                xs, xe = min(max(xs, 0), W), min(max(xe, 0), W)
                bid = (r * ph + by) * pw + bx
                if ye <= ys or xe <= xs:  # empty bin -> contributes 0
                    sb.append(batch_ids[r])
                    syi.append(0)
                    sxi.append(0)
                    bin_id.append(bid + (n_rois * ph * pw))  # dump slot
                    continue
                for yy in range(ys, ye):
                    for xx in range(xs, xe):
                        sb.append(batch_ids[r])
                        syi.append(yy)
                        sxi.append(xx)
                        bin_id.append(bid)

    sb = jnp.asarray(np.array(sb, np.int32))
    syi = jnp.asarray(np.array(syi, np.int32))
    sxi = jnp.asarray(np.array(sxi, np.int32))
    bin_id = jnp.asarray(np.array(bin_id, np.int32))
    n_bins = n_rois * ph * pw

    def fn(x, sb, syi, sxi, bin_id, n_bins, ph, pw):
        g = x[sb, :, syi, sxi]  # [S, C]
        pooled = jax.ops.segment_max(g, bin_id,
                                     num_segments=2 * n_bins)[:n_bins]
        pooled = jnp.where(jnp.isfinite(pooled), pooled, 0.0)
        out = pooled.reshape(-1, ph, pw, pooled.shape[-1])
        return jnp.transpose(out, (0, 3, 1, 2))

    return _op("roi_pool", fn, x, sb, syi, sxi, bin_id,
               n_bins=n_bins, ph=ph, pw=pw)


# -- box coder --------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Reference vision/ops.py:584 — encode/decode center-size deltas."""
    norm = 0.0 if box_normalized else 1.0
    if isinstance(prior_box_var, (list, tuple)):
        var = jnp.asarray(np.array(prior_box_var, np.float32))
        var_is_tensor = False
    else:
        var = prior_box_var
        var_is_tensor = True

    if code_type == "encode_center_size":
        def fn(p, v, t, norm):
            pw = p[:, 2] - p[:, 0] + norm
            ph_ = p[:, 3] - p[:, 1] + norm
            px = p[:, 0] + pw * 0.5
            py = p[:, 1] + ph_ * 0.5
            tw = t[:, None, 2] - t[:, None, 0] + norm
            th = t[:, None, 3] - t[:, None, 1] + norm
            tx = t[:, None, 0] + tw * 0.5
            ty = t[:, None, 1] + th * 0.5
            v = jnp.broadcast_to(v.reshape(-1, 4) if v.ndim == 1
                                 else v, p.shape)
            ox = (tx - px[None, :]) / pw[None, :] / v[None, :, 0]
            oy = (ty - py[None, :]) / ph_[None, :] / v[None, :, 1]
            ow = jnp.log(jnp.abs(tw / pw[None, :])) / v[None, :, 2]
            oh = jnp.log(jnp.abs(th / ph_[None, :])) / v[None, :, 3]
            return jnp.stack([ox, oy, ow, oh], axis=-1)

        return _op("box_encode", fn, prior_box, var, target_box,
                   norm=norm)

    if code_type == "decode_center_size":
        def fn(p, v, t, norm, axis):
            if p.ndim == 2:
                p = jnp.expand_dims(p, axis)  # [1,M,4] or [N,1,4]
            vv = v
            if vv.ndim == 1:
                vv = jnp.broadcast_to(vv, p.shape)
            elif vv.ndim == 2:
                vv = jnp.expand_dims(vv, axis)
                vv = jnp.broadcast_to(vv, (t.shape[0],) + p.shape[1:]) \
                    if p.shape[0] == 1 else vv
            pw = p[..., 2] - p[..., 0] + norm
            ph_ = p[..., 3] - p[..., 1] + norm
            px = p[..., 0] + pw * 0.5
            py = p[..., 1] + ph_ * 0.5
            ox = vv[..., 0] * t[..., 0] * pw + px
            oy = vv[..., 1] * t[..., 1] * ph_ + py
            ow = jnp.exp(vv[..., 2] * t[..., 2]) * pw
            oh = jnp.exp(vv[..., 3] * t[..., 3]) * ph_
            return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                              ox + ow * 0.5 - norm,
                              oy + oh * 0.5 - norm], axis=-1)

        return _op("box_decode", fn, prior_box, var, target_box,
                   norm=norm, axis=int(axis))

    raise ValueError(f"unknown code_type {code_type!r}")


def _bilinear_sample(x, fy, fx):
    """x [B, C, H, W]; fy/fx [B, ...] float coords -> [B, C, ...]
    bilinear samples, zeros outside."""
    import jax

    B, C, H, W = x.shape

    def gather(iy, ix):
        inb = ((iy >= 0) & (iy < H) & (ix >= 0) & (ix < W))
        iyc = jnp.clip(iy, 0, H - 1)
        ixc = jnp.clip(ix, 0, W - 1)
        vals = jax.vmap(lambda img, jy, jx: img[:, jy, jx])(x, iyc, ixc)
        return vals * inb[:, None].astype(x.dtype)

    y0 = jnp.floor(fy).astype(jnp.int32)
    x0 = jnp.floor(fx).astype(jnp.int32)
    wy = (fy - y0)[:, None].astype(x.dtype)
    wx = (fx - x0)[:, None].astype(x.dtype)
    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference vision/ops.py
    deform_conv2d; phi deformable_conv kernel).

    x [B, Cin, H, W]; offset [B, 2*dg*Kh*Kw, Ho, Wo] as (dy, dx) pairs
    per tap; mask [B, dg*Kh*Kw, Ho, Wo] (v2 modulation) or None (v1).

    TPU-native: each kernel tap is a bilinear gather at its offset
    position; the taps stack into [B, Cin*Kh*Kw, Ho, Wo] and ONE einsum
    against the weight does the contraction on the MXU.
    """
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else dilation

    def fn(x, offset, weight, mask, sh, sw, ph, pw, dh, dw, dg, groups,
           has_mask):
        B, Cin, H, W = x.shape
        Cout, Cin_g, Kh, Kw = weight.shape
        Ho = (H + 2 * ph - (dh * (Kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (Kw - 1) + 1)) // sw + 1
        K = Kh * Kw
        off = offset.reshape(B, dg, K, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, :]
        ky = (jnp.arange(Kh) * dh)[:, None].repeat(Kw, 1).reshape(K)
        kx = (jnp.arange(Kw) * dw)[None, :].repeat(Kh, 0).reshape(K)
        cg = Cin // dg
        samples = []
        for g in range(dg):
            fy = (base_y + ky[:, None, None]
                  + off[:, g, :, 0])                   # [B, K, Ho, Wo]
            fx = base_x + kx[:, None, None] + off[:, g, :, 1]
            xs = x[:, g * cg:(g + 1) * cg]
            s = _bilinear_sample(
                xs, fy.reshape(B, -1), fx.reshape(B, -1)).reshape(
                B, cg, K, Ho, Wo)
            if has_mask:
                s = s * mask.reshape(B, dg, K, Ho, Wo)[:, g][:, None]
            samples.append(s)
        sampled = jnp.concatenate(samples, axis=1)  # [B, Cin, K, Ho, Wo]
        # grouped contraction: [B, Cin, K, Ho, Wo] x [Cout, Cin/g, K]
        w2 = weight.reshape(Cout, Cin_g, K)
        if groups == 1:
            out = jnp.einsum("bckhw,ock->bohw", sampled, w2)
        else:
            co_g = Cout // groups
            outs = []
            for g in range(groups):
                outs.append(jnp.einsum(
                    "bckhw,ock->bohw",
                    sampled[:, g * Cin_g:(g + 1) * Cin_g],
                    w2[g * co_g:(g + 1) * co_g]))
            out = jnp.concatenate(outs, axis=1)
        return out

    out = _op("deform_conv2d", fn, _t_in(x), _t_in(offset), _t_in(weight),
              _t_in(mask) if mask is not None else _t_in(
                  jnp.zeros((1,), jnp.float32)),
              sh=int(sh), sw=int(sw), ph=int(ph), pw=int(pw),
              dh=int(dh), dw=int(dw), dg=int(deformable_groups),
              groups=int(groups), has_mask=mask is not None)
    if bias is not None:
        from ..ops import reshape as _rs

        out = out + _rs(_t_in(bias), [1, -1, 1, 1])
    return out


def _t_in(v):
    from ..core.tensor import Tensor

    return v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))


from ..nn import initializer as _I  # noqa: E402
from ..nn.layers import Layer as _Layer  # noqa: E402


class DeformConv2D(_Layer):
    """Layer form (reference vision/ops.py DeformConv2D): the caller
    supplies offset (and mask for v2) at forward time."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1,
                 deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) \
            if isinstance(kernel_size, int) else kernel_size
        self._cfg = (stride, padding, dilation, deformable_groups,
                     groups)
        bound = 1.0 / math.sqrt(in_channels * kh * kw)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr,
            default_initializer=_I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=_I.Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        stride, padding, dilation, dg, groups = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=stride, padding=padding,
                             dilation=dilation,
                             deformable_groups=dg, groups=groups,
                             mask=mask)


# --- declared-__all__ detection tail (VERDICT r4 missing #2) ---------------
# yolo_box/yolo_loss/prior_box/matrix_nms/generate_proposals/
# distribute_fpn_proposals/psroi_pool + RoI layer classes + image io.
# Reference: python/paddle/vision/ops.py:69 (yolo_loss), :277 (yolo_box),
# :438 (prior_box), :1175 (distribute_fpn_proposals), :2108
# (generate_proposals), :1443 (psroi_pool), :2245 (matrix_nms); kernel
# semantics from paddle/phi/kernels/cpu/{yolo_box,yolo_loss,prior_box,
# matrix_nms,generate_proposals}_kernel.cc.


def _sig(v):
    return 1.0 / (1.0 + jnp.exp(-v))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """YOLOv3 head decode (kernel: funcs/yolo_box_util.h GetYoloBox —
    b = (cell + sigmoid(t)*scale + bias) · img/grid, p·e^t anchors;
    boxes under conf_thresh are zeroed)."""
    xv = jnp.asarray(x._data if isinstance(x, Tensor) else x)
    imgs = np.asarray(_np(img_size), np.int32)
    an = np.asarray(anchors, np.int32).reshape(-1, 2)
    an_num = an.shape[0]
    N, C, H, W = xv.shape
    in_h, in_w = downsample_ratio * H, downsample_ratio * W
    scale, bias = float(scale_x_y), -0.5 * (float(scale_x_y) - 1.0)

    if iou_aware:
        iou_logits = xv[:, :an_num].reshape(N, an_num, H, W)
        body = xv[:, an_num:].reshape(N, an_num, 5 + class_num, H, W)
    else:
        body = xv.reshape(N, an_num, 5 + class_num, H, W)

    cx = jnp.arange(W, dtype=xv.dtype)[None, None, None, :]
    cy = jnp.arange(H, dtype=xv.dtype)[None, None, :, None]
    img_w = jnp.asarray(imgs[:, 1], xv.dtype)[:, None, None, None]
    img_h = jnp.asarray(imgs[:, 0], xv.dtype)[:, None, None, None]

    bx = (cx + _sig(body[:, :, 0]) * scale + bias) * img_w / W
    by = (cy + _sig(body[:, :, 1]) * scale + bias) * img_h / H
    bw = jnp.exp(body[:, :, 2]) * \
        jnp.asarray(an[:, 0], xv.dtype)[None, :, None, None] * img_w / in_w
    bh = jnp.exp(body[:, :, 3]) * \
        jnp.asarray(an[:, 1], xv.dtype)[None, :, None, None] * img_h / in_h

    conf = _sig(body[:, :, 4])
    if iou_aware:
        iou = _sig(iou_logits)
        conf = conf ** (1.0 - iou_aware_factor) * iou ** iou_aware_factor
    keep = conf >= conf_thresh

    x1, y1 = bx - bw / 2, by - bh / 2
    x2, y2 = bx + bw / 2, by + bh / 2
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=2) * \
        keep[:, :, None].astype(xv.dtype)
    scores = conf[:, :, None] * _sig(body[:, :, 5:])
    scores = scores * keep[:, :, None].astype(xv.dtype)
    # layout matches the kernel: anchors-major over grid cells
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(N, an_num * H * W, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(
        N, an_num * H * W, class_num)
    return Tensor(boxes), Tensor(scores)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (kernel cpu/yolo_loss_kernel.cc): location
    BCE+L1 at matched cells, class BCE, objectness BCE with
    ignore-region masking.  Vectorized jnp (differentiable w.r.t. x via
    jax AD — the reference pairs a hand-written grad kernel)."""
    xv = jnp.asarray(x._data if isinstance(x, Tensor) else x)
    gtb = jnp.asarray(_np(gt_box), jnp.float32)      # [N, B, 4] xywh rel
    gtl = np.asarray(_np(gt_label), np.int64)        # [N, B]
    gts = (jnp.asarray(_np(gt_score), jnp.float32)
           if gt_score is not None
           else jnp.ones(gtl.shape, jnp.float32))
    an = np.asarray(anchors, np.float64).reshape(-1, 2)
    mask = list(anchor_mask)
    mask_num = len(mask)
    N, C, H, W = xv.shape
    input_size = downsample_ratio * H
    scale, bias = float(scale_x_y), -0.5 * (float(scale_x_y) - 1.0)
    body = xv.reshape(N, mask_num, 5 + class_num, H, W).astype(
        jnp.float32)

    if use_label_smooth:
        sw = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - sw, sw
    else:
        label_pos, label_neg = 1.0, 0.0

    def bce(logit, label):
        return jnp.maximum(logit, 0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    valid = (gtb[:, :, 2] > 1e-6) & (gtb[:, :, 3] > 1e-6)   # [N, B]

    # --- predicted boxes (relative units) for the ignore mask ---------
    cx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    cy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    anm = np.asarray([an[m] for m in mask], np.float32)  # [mask_num, 2]
    px = (cx + _sig(body[:, :, 0]) * scale + bias) / W
    py = (cy + _sig(body[:, :, 1]) * scale + bias) / H
    pw = jnp.exp(body[:, :, 2]) * anm[None, :, 0, None, None] / input_size
    phh = jnp.exp(body[:, :, 3]) * anm[None, :, 1, None, None] / input_size

    def iou_xywh(x1, y1, w1, h1, x2, y2, w2, h2):
        ow = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2) - \
            jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
        oh = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2) - \
            jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
        inter = jnp.where((ow < 0) | (oh < 0), 0.0, ow * oh)
        return inter / (w1 * h1 + w2 * h2 - inter)

    # best IoU of each prediction vs any valid gt: [N,mask,H,W,B]
    ious = iou_xywh(px[..., None], py[..., None], pw[..., None],
                    phh[..., None],
                    gtb[:, None, None, None, :, 0],
                    gtb[:, None, None, None, :, 1],
                    gtb[:, None, None, None, :, 2],
                    gtb[:, None, None, None, :, 3])
    ious = jnp.where(valid[:, None, None, None, :], ious, 0.0)
    best_iou = ious.max(-1)                              # [N,mask,H,W]
    ignore = best_iou > ignore_thresh

    # --- per-gt best anchor (over ALL anchors, shape-only IoU) --------
    an_w = jnp.asarray(an[:, 0], jnp.float32) / input_size
    an_h = jnp.asarray(an[:, 1], jnp.float32) / input_size
    shape_iou = iou_xywh(
        jnp.zeros(()), jnp.zeros(()), gtb[:, :, 2, None],
        gtb[:, :, 3, None], jnp.zeros(()), jnp.zeros(()),
        an_w[None, None, :], an_h[None, None, :])        # [N,B,an_num]
    best_n = jnp.argmax(shape_iou, -1)                    # [N,B]
    mask_arr = np.full(an.shape[0], -1, np.int64)
    for mi, a in enumerate(mask):
        mask_arr[a] = mi
    gt_mask_idx = jnp.asarray(mask_arr)[best_n]           # [N,B]
    matched = valid & (gt_mask_idx >= 0)

    gi = jnp.clip((gtb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)

    # gather predicted entries at matched cells: body[n, mi, :, gj, gi]
    nidx = jnp.arange(N)[:, None]
    sel = body[nidx, jnp.maximum(gt_mask_idx, 0), :, gj, gi]  # [N,B,5+c]

    tx = gtb[:, :, 0] * W - gi
    ty = gtb[:, :, 1] * H - gj
    anm_all = jnp.stack([an_w, an_h], -1) * input_size    # [an_num, 2]
    tw = jnp.log(jnp.maximum(
        gtb[:, :, 2] * input_size / anm_all[best_n, 0], 1e-9))
    th = jnp.log(jnp.maximum(
        gtb[:, :, 3] * input_size / anm_all[best_n, 1], 1e-9))
    loc_scale = (2.0 - gtb[:, :, 2] * gtb[:, :, 3]) * gts
    mfl = matched.astype(jnp.float32)
    loc_loss = (bce(sel[:, :, 0], tx) + bce(sel[:, :, 1], ty) +
                jnp.abs(sel[:, :, 2] - tw) +
                jnp.abs(sel[:, :, 3] - th)) * loc_scale * mfl

    labels = jnp.asarray(gtl)
    onehot = jax.nn.one_hot(labels, class_num, dtype=jnp.float32)
    target = onehot * label_pos + (1 - onehot) * label_neg
    cls_loss = (bce(sel[:, :, 5:], target).sum(-1) * gts * mfl)

    # objectness target map: score at matched cells, -1 in ignore zone.
    # Unmatched gt rows scatter to an out-of-range index (mode="drop")
    # so a padded row can never clobber a matched row's target.
    obj = jnp.where(ignore, -1.0, 0.0)                    # [N,mask,H,W]
    flat = obj.reshape(N, -1)
    pos = (jnp.maximum(gt_mask_idx, 0) * H + gj) * W + gi  # [N,B]
    pos = jnp.where(matched, pos, flat.shape[1])
    flat = flat.at[nidx, pos].set(gts, mode="drop")
    obj = flat.reshape(N, mask_num, H, W)

    obj_logit = body[:, :, 4]
    obj_loss = jnp.where(
        obj > 1e-5, bce(obj_logit, 1.0) * obj,
        jnp.where(obj > -0.5, bce(obj_logit, 0.0), 0.0))

    loss = loc_loss.sum((1,)) + cls_loss.sum((1,)) + obj_loss.sum(
        (1, 2, 3))
    return Tensor(loss)


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (kernel cpu/prior_box_kernel.cc).  Returns
    (boxes [H,W,num_priors,4], variances same shape)."""
    # only the static shapes are needed — no device fetch
    fH, fW = tuple(input.shape)[2:]
    iH, iW = tuple(image.shape)[2:]
    min_sizes = [float(m) for m in np.atleast_1d(min_sizes)]
    max_sizes = [] if max_sizes is None else \
        [float(m) for m in np.atleast_1d(max_sizes)]
    # ExpandAspectRatios: 1.0 first, then unseen ratios (+ flips)
    ars = [1.0]
    for ar in np.atleast_1d(aspect_ratios):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    step_w = float(steps[0]) or iW / fW
    step_h = float(steps[1]) or iH / fH

    num_priors = len(ars) * len(min_sizes) + len(max_sizes)
    out = np.zeros((fH, fW, num_priors, 4), np.float32)
    centers_x = (np.arange(fW) + offset) * step_w
    centers_y = (np.arange(fH) + offset) * step_h
    cx = centers_x[None, :]
    cy = centers_y[:, None]

    def put(k, bw, bh):
        out[:, :, k, 0] = (cx - bw) / iW
        out[:, :, k, 1] = (cy - bh) / iH
        out[:, :, k, 2] = (cx + bw) / iW
        out[:, :, k, 3] = (cy + bh) / iH

    k = 0
    for s, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            put(k, ms / 2.0, ms / 2.0)
            k += 1
            if max_sizes:
                sz = math.sqrt(ms * max_sizes[s]) / 2.0
                put(k, sz, sz)
                k += 1
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                put(k, ms * math.sqrt(ar) / 2.0, ms / math.sqrt(ar) / 2.0)
                k += 1
        else:
            for ar in ars:
                put(k, ms * math.sqrt(ar) / 2.0, ms / math.sqrt(ar) / 2.0)
                k += 1
            if max_sizes:
                sz = math.sqrt(ms * max_sizes[s]) / 2.0
                put(k, sz, sz)
                k += 1
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(
        np.asarray(variance, np.float32), out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def _box_area(b, normalized):
    off = 0.0 if normalized else 1.0
    return (b[..., 2] - b[..., 0] + off) * (b[..., 3] - b[..., 1] + off)


def _pair_iou(a, b, normalized):
    """IoU between each row of a [n,4] and b [m,4] -> [n,m]."""
    off = 0.0 if normalized else 1.0
    x1 = np.maximum(a[:, None, 0], b[None, :, 0])
    y1 = np.maximum(a[:, None, 1], b[None, :, 1])
    x2 = np.minimum(a[:, None, 2], b[None, :, 2])
    y2 = np.minimum(a[:, None, 3], b[None, :, 3])
    iw = np.maximum(x2 - x1 + off, 0)
    ih = np.maximum(y2 - y1 + off, 0)
    inter = iw * ih
    return inter / (_box_area(a, normalized)[:, None] +
                    _box_area(b, normalized)[None] - inter + 1e-12)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """SOLOv2 Matrix NMS (kernel cpu/matrix_nms_kernel.cc): scores decay
    by min over higher-ranked overlaps of decay(iou, max_iou) —
    gaussian exp((max²−iou²)·σ) or linear (1−iou)/(1−max)."""
    bb = _np(bboxes).astype(np.float64)    # [N, M, 4]
    sc = _np(scores).astype(np.float64)    # [N, C, M]
    N, C, M = sc.shape
    outs, idxs, rois_num = [], [], []
    for n in range(N):
        all_rows, all_idx = [], []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            perm = np.nonzero(s > score_threshold)[0]
            if perm.size == 0:
                continue
            perm = perm[np.argsort(-s[perm], kind="stable")]
            if nms_top_k > -1 and perm.size > nms_top_k:
                perm = perm[:nms_top_k]
            boxes = bb[n, perm]
            iou = _pair_iou(boxes, boxes, normalized)
            iou = np.tril(iou, -1)               # j < i
            iou_max = np.concatenate([[0.0], iou[1:, :].max(1)])
            if use_gaussian:
                decay = np.exp((iou_max[None, :] ** 2 - iou ** 2) *
                               gaussian_sigma)
            else:
                decay = (1.0 - iou) / (1.0 - iou_max[None, :] + 1e-12)
            with np.errstate(invalid="ignore"):
                min_decay = np.where(
                    np.arange(perm.size)[:, None] >
                    np.arange(perm.size)[None, :],
                    decay, 1.0).min(1)
            min_decay[0] = 1.0
            ds = min_decay * s[perm]
            keep = ds > post_threshold
            for i in np.nonzero(keep)[0]:
                all_rows.append([c, ds[i], *bb[n, perm[i]]])
                all_idx.append(n * M + perm[i])
        if all_rows:
            rows = np.asarray(all_rows, np.float32)
            order = np.argsort(-rows[:, 1], kind="stable")
            if keep_top_k > -1:
                order = order[:keep_top_k]
            rows = rows[order]
            all_idx = np.asarray(all_idx, np.int64)[order]
        else:
            rows = np.zeros((0, 6), np.float32)
            all_idx = np.zeros((0,), np.int64)
        outs.append(rows)
        idxs.append(all_idx)
        rois_num.append(rows.shape[0])
    out = Tensor(jnp.asarray(np.concatenate(outs, 0)
                             if outs else np.zeros((0, 6), np.float32)))
    ret = [out]
    if return_index:
        ret.append(Tensor(jnp.asarray(
            np.concatenate(idxs, 0)[:, None])))
    if return_rois_num:
        ret.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(ret) if len(ret) > 1 else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (R-FCN; reference
    vision/ops.py:1443, kernel cpu/psroi_pool_kernel.cc).  C must equal
    out_channels·ph·pw; bin (i,j) of output channel c pools input
    channel c·ph·pw + i·pw + j."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xv = jnp.asarray(x._data if isinstance(x, Tensor) else x)
    N, C, H, W = xv.shape
    if C % (ph * pw) != 0:
        raise ValueError(
            f"input channels {C} must be divisible by pooled size "
            f"{ph}x{pw}")
    out_ch = C // (ph * pw)
    b = _np(boxes).astype(np.float64)
    n_rois = b.shape[0]
    batch_ids = _roi_batch_ids(boxes_num, n_rois)

    outs = np.zeros((n_rois, out_ch, ph, pw), np.float32)
    feats = None  # lazily fetched once
    for r in range(n_rois):
        # kernel: start rounded down, end rounded up, both scaled
        x1 = round(b[r, 0] * spatial_scale)
        y1 = round(b[r, 1] * spatial_scale)
        x2 = round(b[r, 2] * spatial_scale)
        y2 = round(b[r, 3] * spatial_scale)
        rh = max(y2 - y1, 0.1)
        rw = max(x2 - x1, 0.1)
        bin_h, bin_w = rh / ph, rw / pw
        if feats is None:
            feats = np.asarray(xv)
        for i in range(ph):
            ys = int(np.floor(y1 + i * bin_h))
            ye = int(np.ceil(y1 + (i + 1) * bin_h))
            ys, ye = min(max(ys, 0), H), min(max(ye, 0), H)
            for j in range(pw):
                xs = int(np.floor(x1 + j * bin_w))
                xe = int(np.ceil(x1 + (j + 1) * bin_w))
                xs, xe = min(max(xs, 0), W), min(max(xe, 0), W)
                if ye <= ys or xe <= xs:
                    continue
                chans = np.arange(out_ch) * ph * pw + i * pw + j
                region = feats[batch_ids[r], chans][:, ys:ye, xs:xe]
                outs[r, :, i, j] = region.mean((1, 2))
    return Tensor(jnp.asarray(outs))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by sqrt-area (reference
    vision/ops.py:1175; level = floor(log2(sqrt(area)/refer_scale))
    + refer_level, clamped to [min_level, max_level])."""
    rois = _np(fpn_rois).astype(np.float64)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)

    rn = (_np(rois_num).astype(np.int64) if rois_num is not None
          else np.array([rois.shape[0]], np.int64))
    img_of = np.repeat(np.arange(rn.size), rn)

    multi_rois, restore_src, lvl_rois_num = [], [], []
    for lv in range(min_level, max_level + 1):
        # per-level rois keep image order (kernel iterates images)
        sel = np.nonzero(lvl == lv)[0]
        sel = sel[np.argsort(img_of[sel], kind="stable")]
        multi_rois.append(Tensor(jnp.asarray(
            rois[sel].astype(np.float32))))
        restore_src.extend(sel.tolist())
        lvl_rois_num.append(Tensor(jnp.asarray(np.bincount(
            img_of[sel], minlength=rn.size).astype(np.int32))))
    # restore_ind[orig_row] = position of that row in concat(levels)
    restore = np.empty(rois.shape[0], np.int64)
    restore[np.asarray(restore_src, np.int64)] = \
        np.arange(rois.shape[0])
    restore_t = Tensor(jnp.asarray(restore[:, None].astype(np.int32)))
    if rois_num is not None:
        return multi_rois, restore_t, lvl_rois_num
    return multi_rois, restore_t, None


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference vision/ops.py:2108, kernel
    generate_proposals: top-k score, delta decode, clip, min-size
    filter, greedy NMS, top post_nms_top_n)."""
    sc = _np(scores).astype(np.float64)          # [N, A, H, W]
    bd = _np(bbox_deltas).astype(np.float64)     # [N, 4A, H, W]
    ims = _np(img_size).astype(np.float64)       # [N, 2] (h, w)
    an = _np(anchors).astype(np.float64).reshape(-1, 4)
    va = _np(variances).astype(np.float64).reshape(-1, 4)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # HWA order
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s, kind="stable")
        if pre_nms_top_n > 0:
            order = order[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order], va[order]
        # decode (variance-scaled ctr/size deltas)
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw * 0.5
        acy = a[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        bw = np.exp(np.minimum(v[:, 2] * d[:, 2], np.log(1000 / 16.))) * aw
        bh = np.exp(np.minimum(v[:, 3] * d[:, 3], np.log(1000 / 16.))) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], 1)
        ih, iw = ims[n]
        boxes[:, 0] = np.clip(boxes[:, 0], 0, iw - off)
        boxes[:, 1] = np.clip(boxes[:, 1], 0, ih - off)
        boxes[:, 2] = np.clip(boxes[:, 2], 0, iw - off)
        boxes[:, 3] = np.clip(boxes[:, 3], 0, ih - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        keep = (ws >= min_size) & (hs >= min_size)
        boxes, s = boxes[keep], s[keep]
        # greedy nms
        sel = []
        iou = _pair_iou(boxes, boxes, normalized=not pixel_offset)
        sup = np.zeros(boxes.shape[0], bool)
        for i in range(boxes.shape[0]):
            if sup[i]:
                continue
            sel.append(i)
            if len(sel) >= post_nms_top_n > 0:
                break
            sup |= iou[i] > nms_thresh
            sup[i] = True
        sel = np.asarray(sel, np.int64)
        all_rois.append(boxes[sel].astype(np.float32))
        all_probs.append(s[sel].astype(np.float32))
        nums.append(sel.size)
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)[:, None]))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(
            np.asarray(nums, np.int32)))
    return rois, probs


def read_file(filename, name=None):
    """Read raw file bytes as a uint8 tensor (reference
    vision/ops.py:1347)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference
    vision/ops.py:1390; the reference rides nvjpeg, here PIL)."""
    import io

    from PIL import Image

    raw = bytes(bytearray(np.asarray(_np(x), np.uint8)))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


class RoIPool(_Layer):
    """Layer form of roi_pool (reference vision/ops.py RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class RoIAlign(_Layer):
    """Layer form of roi_align (reference vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class PSRoIPool(_Layer):
    """Layer form of psroi_pool (reference vision/ops.py PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)
