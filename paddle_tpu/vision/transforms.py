"""Minimal vision transforms (reference: python/paddle/vision/transforms)."""
from __future__ import annotations

import numpy as np

def _is_chw(arr):
    """Channel-first heuristic shared by every transform: 3-d with a
    small leading channel count and a non-channel trailing dim."""
    return (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            and arr.shape[-1] not in (1, 3, 4))



class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (x - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        arr = np.asarray(img, dtype=np.float32)
        chw = _is_chw(arr)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + ((arr.shape[-1],) if arr.ndim == 3
                                     else ())
        return np.asarray(jax.image.resize(arr, out_shape, method="linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = _is_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th,
                                                          j:j + tw]


class Pad:
    """Pad all sides (reference transforms.Pad); HWC or CHW arrays."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = (padding,) * 4 if isinstance(padding, int) else (
            tuple(padding) * 2 if len(padding) == 2 else tuple(padding))
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        left, top, right, bottom = self.padding
        chw = _is_chw(arr)
        if chw:
            pads = [(0, 0), (top, bottom), (left, right)]
        elif arr.ndim == 3:
            pads = [(top, bottom), (left, right), (0, 0)]
        else:
            pads = [(top, bottom), (left, right)]
        if self.mode == "constant":
            return np.pad(arr, pads, mode="constant",
                          constant_values=self.fill)
        return np.pad(arr, pads, mode=self.mode)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding is not None:
            arr = Pad(self.padding)(arr)
        chw = _is_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(th - h, 0), max(tw - w, 0)
            arr = Pad((pw, ph, pw, ph))(arr)
            h, w = h + 2 * ph, w + 2 * pw
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[:, i:i + th, j:j + tw] if chw \
            else arr[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        chw = _is_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = arr[:, i:i + ch, j:j + cw] if chw \
                    else arr[i:i + ch, j:j + cw]
                return Resize(self.size)(crop)
        return Resize(self.size)(CenterCrop(min(h, w))(arr))


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        arr = np.asarray(img).astype(np.float32)
        chw = _is_chw(arr)
        wts = np.array([0.299, 0.587, 0.114], np.float32)
        if chw:
            g = np.tensordot(wts, arr[:3], 1)[None]
            return np.repeat(g, self.n, 0) if self.n > 1 else g
        g = arr[..., :3] @ wts
        g = g[..., None]
        return np.repeat(g, self.n, -1) if self.n > 1 else g


class RandomRotation:
    """Rotation by a uniform angle (nearest-neighbor resample — host
    numpy; augmentations run in the input pipeline, not on device)."""

    def __init__(self, degrees, fill=0):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        chw = _is_chw(arr)
        a = arr if not chw else np.moveaxis(arr, 0, -1)
        h, w = a.shape[:2]
        cy, cx = (h - 1) / 2, (w - 1) / 2
        ys, xs = np.mgrid[0:h, 0:w]
        c, s = np.cos(angle), np.sin(angle)
        sy = cy + (ys - cy) * c - (xs - cx) * s
        sx = cx + (ys - cy) * s + (xs - cx) * c
        syi = np.round(sy).astype(int)
        sxi = np.round(sx).astype(int)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        out = np.full_like(a, self.fill)
        out[valid] = a[syi[valid], sxi[valid]]
        return np.moveaxis(out, -1, 0) if chw else out


class ColorJitter:
    """Brightness/contrast/saturation jitter (hue omitted — documented
    subset; reference transforms.ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    def __call__(self, img):
        arr = np.asarray(img).astype(np.float32)
        if self.brightness:
            arr = arr * np.random.uniform(max(0, 1 - self.brightness),
                                          1 + self.brightness)
        if self.contrast:
            f = np.random.uniform(max(0, 1 - self.contrast),
                                  1 + self.contrast)
            arr = (arr - arr.mean()) * f + arr.mean()
        if self.saturation:
            f = np.random.uniform(max(0, 1 - self.saturation),
                                  1 + self.saturation)
            chw = _is_chw(arr)
            axis = 0 if chw else -1
            gray = arr.mean(axis=axis, keepdims=True)
            arr = gray + (arr - gray) * f
        return arr


# --- functional API (reference: python/paddle/vision/transforms/
# functional.py) — host-side numpy: augmentation runs in the input
# pipeline, never on device ------------------------------------------------

def _hwc(arr):
    """Return (HWC-view, was_chw) for 2-d/3-d arrays."""
    arr = np.asarray(arr)
    if arr.ndim == 2:
        return arr[..., None], "hw"
    if _is_chw(arr):
        return np.moveaxis(arr, 0, -1), "chw"
    return arr, "hwc"


def _unhwc(arr, fmt):
    if fmt == "hw":
        return arr[..., 0]
    if fmt == "chw":
        return np.moveaxis(arr, -1, 0)
    return arr


def to_tensor(pic, data_format="CHW"):
    """PIL/ndarray -> float tensor scaled to [0,1] (reference
    functional.to_tensor)."""
    import paddle_tpu as paddle

    return paddle.to_tensor(ToTensor(data_format)(pic))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def hflip(img):
    a, fmt = _hwc(img)
    return _unhwc(a[:, ::-1].copy(), fmt)


def vflip(img):
    a, fmt = _hwc(img)
    return _unhwc(a[::-1].copy(), fmt)


def resize(img, size, interpolation="bilinear"):
    if isinstance(size, int):
        a, fmt = _hwc(img)
        h, w = a.shape[:2]
        if h <= w:
            size = (size, max(1, int(round(w * size / h))))
        else:
            size = (max(1, int(round(h * size / w))), size)
    return Resize(size, interpolation)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


def crop(img, top, left, height, width):
    a, fmt = _hwc(img)
    return _unhwc(a[top:top + height, left:left + width].copy(), fmt)


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def adjust_brightness(img, brightness_factor):
    a = np.asarray(img)
    out = np.asarray(a, np.float32) * float(brightness_factor)
    if np.issubdtype(a.dtype, np.integer):
        return np.clip(out, 0, 255).astype(a.dtype)
    return out


def adjust_contrast(img, contrast_factor):
    a = np.asarray(img)
    f32 = np.asarray(a, np.float32)
    gray_mean = to_grayscale(f32).mean()
    out = (f32 - gray_mean) * float(contrast_factor) + gray_mean
    if np.issubdtype(a.dtype, np.integer):
        return np.clip(out, 0, 255).astype(a.dtype)
    return out


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, -1)
    minc = np.min(rgb, -1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0)
    dz = np.maximum(delta, 1e-12)
    rc, gc, bc = (maxc - r) / dz, (maxc - g) / dz, (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta == 0, 0.0, h / 6.0) % 1.0
    return np.stack([h, s, v], -1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(int) % 6
    choices = np.stack([
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)], 0)
    return np.take_along_axis(
        choices, i[None, ..., None].repeat(3, -1), 0)[0]


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor in [-0.5, 0.5] (reference
    functional.adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a = np.asarray(img)
    hwc, fmt = _hwc(a)
    scale = 255.0 if np.issubdtype(a.dtype, np.integer) else 1.0
    hsv = _rgb_to_hsv(np.asarray(hwc, np.float32) / scale)
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv) * scale
    if np.issubdtype(a.dtype, np.integer):
        out = np.clip(np.round(out), 0, 255)
    return _unhwc(out.astype(a.dtype), fmt)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the [i:i+h, j:j+w] patch with value v (reference
    functional.erase).  v may be a scalar, a per-channel vector, or a full
    [C, h, w] patch (the RandomErasing 'random' fill)."""
    from ..core.tensor import Tensor

    vv = np.asarray(v)
    patch = None  # full [C, h, w] fill
    if vv.ndim >= 2:
        patch = vv.reshape(-1, h, w)
    elif vv.ndim == 1:
        vv = vv.reshape(-1)  # per-channel vector, any input orientation
    if isinstance(img, Tensor):
        import paddle_tpu as paddle

        a = np.array(img.numpy())
        chw = a.ndim == 3 and _is_chw(a)
        if chw:
            pv = patch if patch is not None else (
                vv[:, None, None] if vv.ndim else vv)
            a[:, i:i + h, j:j + w] = np.broadcast_to(
                np.asarray(pv).astype(a.dtype), (a.shape[0], h, w))
        else:
            pv = np.moveaxis(patch, 0, -1) if patch is not None else vv
            a[i:i + h, j:j + w] = np.broadcast_to(
                np.asarray(pv).astype(a.dtype),
                a[i:i + h, j:j + w].shape)
        out = paddle.to_tensor(a)
        if inplace:
            img.set_value(out)
            return img
        return out
    a = np.asarray(img)
    hwc, fmt = _hwc(a)
    hwc = hwc.copy()
    pv = np.moveaxis(patch, 0, -1) if patch is not None else vv
    hwc[i:i + h, j:j + w] = np.broadcast_to(
        np.asarray(pv).astype(a.dtype), (h, w, hwc.shape[-1]))
    out = _unhwc(hwc, fmt)
    if inplace and isinstance(img, np.ndarray):
        img[...] = out
        return img
    return out


def _bilinear_sample(a, sy, sx, fill):
    """Sample HWC array at fractional (sy, sx) grids with bilinear
    interpolation and constant fill outside."""
    h, w = a.shape[:2]
    y0 = np.floor(sy).astype(int)
    x0 = np.floor(sx).astype(int)
    wy = (sy - y0)[..., None]
    wx = (sx - x0)[..., None]
    out = np.zeros(sy.shape + (a.shape[-1],), np.float32)
    fillv = np.broadcast_to(np.asarray(fill, np.float32), a.shape[-1:])
    for dy, dx, wgt in ((0, 0, (1 - wy) * (1 - wx)),
                        (0, 1, (1 - wy) * wx),
                        (1, 0, wy * (1 - wx)),
                        (1, 1, wy * wx)):
        yy, xx = y0 + dy, x0 + dx
        valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        vals = np.where(valid[..., None],
                        a[np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)],
                        fillv)
        out = out + wgt * vals
    return out


def _warp(img, inv33, fill=0, interpolation="bilinear"):
    """Warp by the inverse 3x3 output->input coordinate map."""
    a, fmt = _hwc(img)
    a32 = np.asarray(a, np.float32)
    h, w = a32.shape[:2]
    ys, xs = np.mgrid[0:h, 0:w].astype(np.float32)
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1)
    src = inv33 @ coords
    denom = np.where(np.abs(src[2]) < 1e-12, 1e-12, src[2])
    sx = (src[0] / denom).reshape(h, w)
    sy = (src[1] / denom).reshape(h, w)
    if interpolation == "nearest":
        syi, sxi = np.round(sy).astype(int), np.round(sx).astype(int)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        out = np.full_like(
            a32, np.broadcast_to(np.asarray(fill, np.float32),
                                 a32.shape[-1:]))
        out[valid] = a32[syi[valid], sxi[valid]]
    else:
        out = _bilinear_sample(a32, sy, sx, fill)
    if np.issubdtype(np.asarray(a).dtype, np.integer):
        out = np.clip(np.round(out), 0, 255)
    return _unhwc(out.astype(np.asarray(img).dtype), fmt)


def _affine_inverse(center, angle, translate, scale, shear):
    """Inverse affine matrix for output->input mapping (reference
    functional._get_inverse_affine_matrix semantics)."""
    rot = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # forward: M = T(center) R(rot) Shear Scale T(-center) + translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    fwd = np.array([[scale * a, scale * b, 0.0],
                    [scale * c, scale * d, 0.0],
                    [0.0, 0.0, 1.0]], np.float64)
    pre = np.array([[1, 0, cx + tx], [0, 1, cy + ty], [0, 0, 1]],
                   np.float64)
    post = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float64)
    return np.linalg.inv(pre @ fwd @ post)


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0, 0),
           interpolation="nearest", fill=0, center=None):
    """Affine warp (reference functional.affine)."""
    a, _ = _hwc(img)
    h, w = a.shape[:2]
    if np.isscalar(shear):
        shear = (float(shear), 0.0)
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    inv = _affine_inverse(center, angle, translate, scale, shear)
    return _warp(img, inv, fill, interpolation)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate (reference functional.rotate; expand unsupported keeps the
    input canvas, matching the default)."""
    return affine(img, angle, interpolation=interpolation, fill=fill,
                  center=center)


def _homography(src_pts, dst_pts):
    """3x3 homography H with H @ src ~ dst (4 point pairs)."""
    A, b = [], []
    for (sx, sy), (dx, dy) in zip(src_pts, dst_pts):
        A.append([sx, sy, 1, 0, 0, 0, -dx * sx, -dx * sy])
        b.append(dx)
        A.append([0, 0, 0, sx, sy, 1, -dy * sx, -dy * sy])
        b.append(dy)
    h = np.linalg.solve(np.asarray(A, np.float64),
                        np.asarray(b, np.float64))
    return np.append(h, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp mapping startpoints->endpoints (reference
    functional.perspective: points are [[x, y]] corner lists)."""
    fwd = _homography(startpoints, endpoints)
    return _warp(img, np.linalg.inv(fwd), fill, interpolation)


# --- class transforms over the functional API ------------------------------

class BaseTransform:
    """Keyed-transform protocol (reference transforms.BaseTransform:
    _get_params once, then _apply_<key> per input)."""

    def __init__(self, keys=None):
        self.keys = keys if keys is not None else ("image",)
        self.params = None

    def _get_params(self, inputs):
        return None

    def __call__(self, inputs):
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        self.params = self._get_params(inputs)
        outputs = []
        for i, data in enumerate(inputs):
            key = self.keys[i] if i < len(self.keys) else "image"
            apply_fn = getattr(self, f"_apply_{key}", None)
            outputs.append(data if apply_fn is None else apply_fn(data))
        if len(outputs) == 1:
            return outputs[0]
        return tuple(outputs)

    def _apply_image(self, img):
        return img


class Transpose(BaseTransform):
    """HWC -> CHW (reference transforms.Transpose)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = tuple(order)

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = to_grayscale(np.asarray(img, np.float32),
                            num_output_channels=3)
        a = np.asarray(img, np.float32)
        hwc, fmt = _hwc(a)
        ghwc, _ = _hwc(gray)
        out = ghwc + (hwc - ghwc) * f
        if np.issubdtype(np.asarray(img).dtype, np.integer):
            out = np.clip(np.round(out), 0, 255)
        return _unhwc(out.astype(np.asarray(img).dtype), fmt)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class RandomAffine(BaseTransform):
    """Random affine (reference transforms.RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a, _ = _hwc(img)
        h, w = a.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0],
                                   self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1],
                                   self.translate[1]) * h
        scale = np.random.uniform(*self.scale) if self.scale else 1.0
        shear = (0.0, 0.0)
        if self.shear is not None:
            sh = self.shear
            if np.isscalar(sh):
                sh = (-sh, sh)
            shear = (np.random.uniform(sh[0], sh[1]),
                     np.random.uniform(sh[2], sh[3])
                     if len(sh) == 4 else 0.0)
        return affine(img, angle, (tx, ty), scale, shear,
                      self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    """Random perspective distortion (reference
    transforms.RandomPerspective)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a, _ = _hwc(img)
        h, w = a.shape[:2]
        d = self.distortion_scale
        half_h, half_w = int(d * h / 2), int(d * w / 2)
        def rnd(lo, hi):
            return int(np.random.randint(lo, max(hi, lo + 1)))
        start = [[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]]
        end = [[rnd(0, half_w), rnd(0, half_h)],
               [w - 1 - rnd(0, half_w), rnd(0, half_h)],
               [w - 1 - rnd(0, half_w), h - 1 - rnd(0, half_h)],
               [rnd(0, half_w), h - 1 - rnd(0, half_h)]]
        return perspective(img, start, end, self.interpolation,
                           self.fill)


class RandomErasing(BaseTransform):
    """Random cutout rectangle (reference transforms.RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a, _ = _hwc(np.asarray(
            img.numpy() if hasattr(img, "numpy") else img))
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w and eh > 0 and ew > 0:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                if isinstance(self.value, str):
                    if self.value != "random":
                        raise ValueError(
                            "value only supports 'random' as a string")
                    # reference RandomErasing: per-element normal noise
                    c = a.shape[-1]
                    v = np.random.normal(
                        size=(c, eh, ew)).astype(np.float32)
                elif np.isscalar(self.value):
                    v = self.value
                else:
                    v = np.asarray(self.value, np.float32)
                return erase(img, i, j, eh, ew, v, self.inplace)
        return img
