"""Minimal vision transforms (reference: python/paddle/vision/transforms)."""
from __future__ import annotations

import numpy as np

def _is_chw(arr):
    """Channel-first heuristic shared by every transform: 3-d with a
    small leading channel count and a non-channel trailing dim."""
    return (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            and arr.shape[-1] not in (1, 3, 4))



class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (x - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        arr = np.asarray(img, dtype=np.float32)
        chw = _is_chw(arr)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + ((arr.shape[-1],) if arr.ndim == 3
                                     else ())
        return np.asarray(jax.image.resize(arr, out_shape, method="linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = _is_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th,
                                                          j:j + tw]


class Pad:
    """Pad all sides (reference transforms.Pad); HWC or CHW arrays."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = (padding,) * 4 if isinstance(padding, int) else (
            tuple(padding) * 2 if len(padding) == 2 else tuple(padding))
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        left, top, right, bottom = self.padding
        chw = _is_chw(arr)
        if chw:
            pads = [(0, 0), (top, bottom), (left, right)]
        elif arr.ndim == 3:
            pads = [(top, bottom), (left, right), (0, 0)]
        else:
            pads = [(top, bottom), (left, right)]
        if self.mode == "constant":
            return np.pad(arr, pads, mode="constant",
                          constant_values=self.fill)
        return np.pad(arr, pads, mode=self.mode)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding is not None:
            arr = Pad(self.padding)(arr)
        chw = _is_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(th - h, 0), max(tw - w, 0)
            arr = Pad((pw, ph, pw, ph))(arr)
            h, w = h + 2 * ph, w + 2 * pw
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[:, i:i + th, j:j + tw] if chw \
            else arr[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        chw = _is_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = arr[:, i:i + ch, j:j + cw] if chw \
                    else arr[i:i + ch, j:j + cw]
                return Resize(self.size)(crop)
        return Resize(self.size)(CenterCrop(min(h, w))(arr))


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        arr = np.asarray(img).astype(np.float32)
        chw = _is_chw(arr)
        wts = np.array([0.299, 0.587, 0.114], np.float32)
        if chw:
            g = np.tensordot(wts, arr[:3], 1)[None]
            return np.repeat(g, self.n, 0) if self.n > 1 else g
        g = arr[..., :3] @ wts
        g = g[..., None]
        return np.repeat(g, self.n, -1) if self.n > 1 else g


class RandomRotation:
    """Rotation by a uniform angle (nearest-neighbor resample — host
    numpy; augmentations run in the input pipeline, not on device)."""

    def __init__(self, degrees, fill=0):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        chw = _is_chw(arr)
        a = arr if not chw else np.moveaxis(arr, 0, -1)
        h, w = a.shape[:2]
        cy, cx = (h - 1) / 2, (w - 1) / 2
        ys, xs = np.mgrid[0:h, 0:w]
        c, s = np.cos(angle), np.sin(angle)
        sy = cy + (ys - cy) * c - (xs - cx) * s
        sx = cx + (ys - cy) * s + (xs - cx) * c
        syi = np.round(sy).astype(int)
        sxi = np.round(sx).astype(int)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        out = np.full_like(a, self.fill)
        out[valid] = a[syi[valid], sxi[valid]]
        return np.moveaxis(out, -1, 0) if chw else out


class ColorJitter:
    """Brightness/contrast/saturation jitter (hue omitted — documented
    subset; reference transforms.ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    def __call__(self, img):
        arr = np.asarray(img).astype(np.float32)
        if self.brightness:
            arr = arr * np.random.uniform(max(0, 1 - self.brightness),
                                          1 + self.brightness)
        if self.contrast:
            f = np.random.uniform(max(0, 1 - self.contrast),
                                  1 + self.contrast)
            arr = (arr - arr.mean()) * f + arr.mean()
        if self.saturation:
            f = np.random.uniform(max(0, 1 - self.saturation),
                                  1 + self.saturation)
            chw = _is_chw(arr)
            axis = 0 if chw else -1
            gray = arr.mean(axis=axis, keepdims=True)
            arr = gray + (arr - gray) * f
        return arr
