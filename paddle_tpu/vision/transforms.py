"""Minimal vision transforms (reference: python/paddle/vision/transforms)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (x - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        arr = np.asarray(img, dtype=np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + ((arr.shape[-1],) if arr.ndim == 3
                                     else ())
        return np.asarray(jax.image.resize(arr, out_shape, method="linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th,
                                                          j:j + tw]
