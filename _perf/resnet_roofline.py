"""ResNet-50 roofline attribution (VERDICT r4 next #4).

Builds the exact bench-config train step, pulls XLA's OWN cost analysis
(bytes accessed / flop count) off the compiled executable, measures the
step, and reports achieved HBM bandwidth vs the chip's peak — the
quantified form of the "HBM-roofline-bound" claim.  Output: one JSON
line, recorded into PERF.md and consumed by bench.py's resnet entry.

Run: PYTHONPATH=/root/repo python _perf/resnet_roofline.py
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.models.training import CompiledTrainStep
from paddle_tpu.nn import functional as F
from paddle_tpu.vision.models import resnet50

V5E_PEAK_FLOPS = 394e12       # bf16
V5E_PEAK_HBM = 819e9          # bytes/s


def main():
    model = resnet50(num_classes=1000)
    model.train()
    step = CompiledTrainStep(model, lr=0.1, compute_dtype="bfloat16",
                             loss_fn=F.cross_entropy)
    batch = 256
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.randn(batch, 3, 224, 224), jnp.bfloat16)
    labels = rng.randint(0, 1000, (batch,)).astype(np.int32)

    # one eager step compiles + materializes state
    print("compiling...", file=sys.stderr)
    loss = step.step(imgs, labels)
    _ = float(np.asarray(loss))

    # XLA's cost model for the compiled step program
    sdatas = (step.params, step._master, step._m, step._v,
              jnp.asarray(1.0, jnp.float32),
              jnp.full((1,), 0.1, jnp.float32))
    lowered = step._step.lower(step.params, step._master, step._m,
                               step._v, jnp.asarray(1.0, jnp.float32),
                               0.1, imgs, labels)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    bytes_accessed = float(ca.get("bytes accessed", 0.0))
    flops = float(ca.get("flops", 0.0))

    # measure (differenced run-lengths; _fetch-style device_get sync)
    def run(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = step.step(imgs, labels)
        _ = float(np.asarray(out))
        return time.perf_counter() - t0

    run(3)
    t1, t2 = run(5), run(10)
    dt = (t2 - t1) / 5

    achieved_bw = bytes_accessed / dt
    achieved_flops = flops / dt
    out = {
        "config": "resnet50 b256 224px bf16 (bench config 1)",
        "step_ms": round(dt * 1e3, 2),
        "imgs_per_s": round(batch / dt, 1),
        "xla_bytes_accessed_per_step_gb": round(bytes_accessed / 1e9, 2),
        "xla_flops_per_step_g": round(flops / 1e9, 1),
        "achieved_hbm_gb_s": round(achieved_bw / 1e9, 1),
        "hbm_peak_gb_s": V5E_PEAK_HBM / 1e9,
        "hbm_utilization": round(achieved_bw / V5E_PEAK_HBM, 3),
        "achieved_tflops": round(achieved_flops / 1e12, 1),
        "mxu_peak_tflops": V5E_PEAK_FLOPS / 1e12,
        "mxu_utilization": round(achieved_flops / V5E_PEAK_FLOPS, 3),
        "model_mfu": round(batch / dt * 3 * 4.1e9 / V5E_PEAK_FLOPS, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
