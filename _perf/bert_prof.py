"""Profile the BERT bench step: device-op breakdown by category."""
import glob, gzip, json, os, re, sys, time
from collections import defaultdict

import numpy as np

sys.path.insert(0, "/root/repo")
from paddle_tpu.utils import enable_compile_cache
enable_compile_cache()
import jax


def main():
    from paddle_tpu import nn
    from paddle_tpu.models.bert import BertConfig, BertForQuestionAnswering
    from paddle_tpu.models.training import CompiledTrainStep

    cfg = BertConfig.base()

    class QATrain(nn.Layer):
        def __init__(self):
            super().__init__()
            self.qa = BertForQuestionAnswering(cfg)

        def forward(self, ids, starts, ends):
            return self.qa(ids, start_positions=starts, end_positions=ends)

    model = QATrain()
    model.train()
    step = CompiledTrainStep(model, lr=3e-5, compute_dtype="bfloat16",
                             remat=os.environ.get("REMAT", "1") == "1")
    batch, seq = int(os.environ.get("B", "48")), 384
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    starts = rng.randint(0, seq, (batch,)).astype(np.int32)
    ends = rng.randint(0, seq, (batch,)).astype(np.int32)

    loss = step.step(ids, starts, ends)
    jax.block_until_ready(getattr(loss, "_data", loss))
    t0 = time.perf_counter()
    loss = step.multi_step(10, ids, starts, ends)
    jax.block_until_ready(getattr(loss, "_data", loss))
    print(f"multi compile+run {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    loss = step.multi_step(10, ids, starts, ends)
    jax.block_until_ready(getattr(loss, "_data", loss))
    dt = (time.perf_counter() - t0) / 10
    print(f"step {dt*1e3:.1f} ms, {batch/dt:.1f} seq/s", flush=True)

    logdir = "/tmp/bert_trace"
    os.system(f"rm -rf {logdir}")
    with jax.profiler.trace(logdir):
        loss = step.multi_step(10, ids, starts, ends)
        jax.block_until_ready(getattr(loss, "_data", loss))

    paths = glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True)
    if not paths:
        print("no trace captured", flush=True)
        return
    with gzip.open(paths[0], "rt") as f:
        trace = json.load(f)
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in trace.get("traceEvents", [])
                 if e.get("ph") == "M" and e.get("name") == "process_name"
                 and "args" in e}
    dev_pids = {p for p, n in pid_names.items() if "TPU" in n}
    events = [e for e in trace["traceEvents"]
              if e.get("ph") == "X" and e.get("dur")
              and e.get("pid") in dev_pids
              and "bytes_accessed" in e.get("args", {})]
    agg = defaultdict(lambda: [0.0, 0, 0])
    for e in events:
        cat = e["args"].get("hlo_category", "?")
        agg[cat][0] += e["dur"]; agg[cat][1] += 1
        agg[cat][2] += int(e["args"]["bytes_accessed"])
    print("category breakdown over 10 steps:")
    for cat, (us, c, b) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        print(f"  {us/10000:8.2f} ms/step x{c//10:4d} {b/10/1e9:6.2f} GB  {cat}")
    big = sorted(events, key=lambda e: -e["dur"])[:12]
    seen = set()
    for e in big:
        n = e["name"]
        if n in seen: continue
        seen.add(n)
        print(f"{e['dur']/1000:7.2f} ms {n[:40]} :: {e['args'].get('long_name','')[:160]}")


if __name__ == "__main__":
    main()
