"""Microbench: einsum vs flash attention at the bench shape (fwd+bwd).

Timing protocol: chain iterations through a data dependency and force a
host transfer at the end (block_until_ready alone does not sync through
the axon tunnel).
"""
import time
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from paddle_tpu.ops.nn_ops import _sdpa_plain


def bench(fn, args, iters=30):
    out = fn(*args)
    _ = np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0]
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _ = np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0]
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    B, H, S, D = 8, 16, 2048, 128
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)

    def mk(impl, blocks=None):
        def loss(q, k, v):
            out = _sdpa_plain(q, k, v, causal=True, impl=impl,
                              flash_blocks=blocks)
            return jnp.sum(out.astype(jnp.float32))
        return jax.jit(loss), jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    print("shape B%d H%d S%d D%d bf16 causal" % (B, H, S, D))
    # useful flops (causal): fwd = 2 mms * 2*B*H*S*S*D / 2
    fwd_fl = 2 * 2 * B * H * S * S * D / 2
    configs = [("einsum", None)]
    for bq, bk in [(128, 128), (256, 512), (512, 512), (512, 1024),
                   (1024, 1024), (512, 2048), (2048, 2048)]:
        configs.append(("flash", (bq, bk)))
    for impl, blocks in configs:
        tag = impl if blocks is None else "flash %4d/%4d" % blocks
        try:
            f, g = mk(impl, blocks)
            tf = bench(f, (q, k, v))
            tg = bench(g, (q, k, v))
            print("%-16s fwd %7.2f ms (%5.1f TF/s)  fwd+bwd %7.2f ms"
                  % (tag, tf, fwd_fl / tf / 1e9, tg))
        except Exception as e:
            print("%-16s FAILED: %s" % (tag, str(e)[:120]))


if __name__ == "__main__":
    main()
