"""Profile the ResNet-50 bench step on the chip: capture an xprof trace
and print the device-op time breakdown by category.
"""
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, "/root/repo")

from paddle_tpu.utils import enable_compile_cache

enable_compile_cache()

import jax  # noqa: E402


def main():
    from paddle_tpu.models.training import CompiledTrainStep
    from paddle_tpu.nn import functional as F
    from paddle_tpu.vision.models import resnet50
    import jax.numpy as jnp

    model = resnet50(num_classes=1000)
    model.train()
    step = CompiledTrainStep(model, lr=0.1, compute_dtype="bfloat16",
                             loss_fn=F.cross_entropy)
    batch = int(os.environ.get("B", "256"))
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.randn(batch, 3, 224, 224), jnp.bfloat16)
    labels = rng.randint(0, 1000, (batch,)).astype(np.int32)

    loss = step.step(imgs, labels)
    jax.block_until_ready(getattr(loss, "_data", loss))
    loss = step.step(imgs, labels)
    jax.block_until_ready(getattr(loss, "_data", loss))

    t0 = time.perf_counter()
    for _ in range(10):
        loss = step.step(imgs, labels)
    jax.block_until_ready(getattr(loss, "_data", loss))
    dt = (time.perf_counter() - t0) / 10
    print(f"step {dt*1e3:.1f} ms, {batch/dt:.0f} imgs/s", flush=True)

    logdir = "/tmp/resnet_trace"
    os.system(f"rm -rf {logdir}")
    with jax.profiler.trace(logdir):
        for _ in range(3):
            loss = step.step(imgs, labels)
        jax.block_until_ready(getattr(loss, "_data", loss))

    # find trace.json.gz and aggregate device events
    paths = glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True)
    if not paths:
        print("no trace captured", flush=True)
        return
    with gzip.open(paths[0], "rt") as f:
        trace = json.load(f)
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X" and e.get("dur")]
    # device events live on TPU pids; find pids whose name mentions TPU
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in trace.get("traceEvents", [])
                 if e.get("ph") == "M" and e.get("name") == "process_name"
                 and "args" in e}
    dev_pids = {p for p, n in pid_names.items()
                if "TPU" in n or "tpu" in n or "/device" in n}
    agg = defaultdict(float)
    for e in events:
        if dev_pids and e["pid"] not in dev_pids:
            continue
        name = e.get("name", "?")
        agg[name] += e["dur"]
    top = sorted(agg.items(), key=lambda kv: -kv[1])[:40]
    total = sum(agg.values())
    print(f"total device us over 3 steps: {total:.0f}")
    for name, us in top:
        print(f"{us/3000:9.2f} ms/step  {name[:110]}")


if __name__ == "__main__":
    main()
